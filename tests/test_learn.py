"""The learning loop (ggrs_tpu/learn/): journal -> dataset -> trainer ->
registry -> hot-swap, pinned end to end.

Dataset extraction is held to the journal's durability edge cases (empty
journal, torn tail, mid-rotation segment boundary, disconnect dummy rows
severing runs) and to the determinism claim that makes fleet journals
usable as training data at all: the SAME seeded match journaled by a
sharded host and a single-device host extracts byte-identical example
tensors.

The acceptance surface is the full loop: journal a seeded starved fleet,
train an ArrayInputModel on the WAL, publish/load through the registry,
hot-swap it into a LIVE speculating host at a tick boundary mid-serve —
the trained model's speculation hit rate must meet or beat the online
Counter model's on the same seeded starved traffic, while the host stays
a bitwise replica of a never-speculating twin ACROSS the swap (single
device and sharded)."""

import os

import numpy as np
import pytest

from ggrs_tpu.errors import ModelIncompatible
from ggrs_tpu.journal.wal import JournalWriter, scan_journal
from ggrs_tpu.learn import (
    ArrayInputModel,
    JournalDataset,
    ModelRegistry,
    extract_examples,
    train_from_journal,
    train_on_examples,
)
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.serve import SessionHost
from ggrs_tpu.serve.loadgen import (
    build_matches,
    drive_scripted,
    held_scripts,
    starve_on_tick,
    sync_fleet,
)
from ggrs_tpu.utils.clock import FakeClock

from test_speculation import ENTITIES, assert_bitwise_twin, run_starved

SEED = 7
TICKS = 90


# ----------------------------------------------------------------------
# extraction semantics
# ----------------------------------------------------------------------


def _toggle_inputs(a=5, b=9, hold=6, cycles=8):
    """u8[F, 1, 1] toggle stream: `hold` frames of a, `hold` of b, ..."""
    vals = []
    for c in range(cycles):
        vals += [a if c % 2 == 0 else b] * hold
    F = len(vals)
    inputs = np.array(vals, dtype=np.uint8).reshape(F, 1, 1)
    statuses = np.zeros((F, 1), dtype=np.int32)
    return inputs, statuses


def test_extract_examples_runs_and_switches():
    inputs, statuses = _toggle_inputs(hold=3, cycles=2)  # 5,5,5,9,9,9
    ex = extract_examples(inputs, statuses)
    # frame 0 starts tracking without emitting
    assert not ex["valid"][0, 0]
    assert ex["valid"][0, 1:].all()
    # holds at run 1,2 then the switch at run 3, then holds again
    assert ex["run"][0, 1:].tolist() == [1, 2, 3, 1, 2]
    assert ex["switched"][0].tolist() == [False, False, False, True, False,
                                          False]
    assert ex["src"][0, 3, 0] == 5 and ex["dst"][0, 3, 0] == 9


def test_extract_disconnect_severs_runs():
    """DISCONNECTED dummy rows are not player behavior: they sever the
    run exactly like InputHistoryModel.break_run — no switch example is
    emitted across the gap, and tracking restarts after it."""
    inputs = np.array(
        [5, 5, 5, 5, 0, 0, 7, 7, 7, 7], dtype=np.uint8
    ).reshape(10, 1, 1)
    statuses = np.zeros((10, 1), dtype=np.int32)
    statuses[4:6, 0] = 2  # DISCONNECTED dummy rows
    ex = extract_examples(inputs, statuses)
    # no 5 -> 7 transition ever recorded
    assert ex["switched"].sum() == 0
    # severed frames and both run-starting frames are invalid
    assert ex["valid"][0].tolist() == [
        False, True, True, True,          # run of 5 (frame 0 starts it)
        False, False,                     # the gap
        False, True, True, True,          # run of 7 restarts tracking
    ]
    # the restarted run counts from 1, not from the pre-gap length
    assert ex["run"][0, 7:].tolist() == [1, 2, 3]

    # control: the same stream WITHOUT the disconnect does record the
    # value change as a switch
    statuses[:] = 0
    ex2 = extract_examples(inputs, statuses)
    assert ex2["switched"].sum() == 2  # 5->0 and 0->7


# ----------------------------------------------------------------------
# journal edge cases: empty, torn tail, mid-rotation boundary
# ----------------------------------------------------------------------


def _write_journal(path, inputs, statuses, *, segment_bytes=1 << 18,
                   meta=None):
    w = JournalWriter(
        path,
        meta=dict(meta or {"num_players": int(inputs.shape[1]),
                           "input_size": int(inputs.shape[2]),
                           "first_frame": 0}),
        segment_bytes=segment_bytes,
    )
    # one record per frame so a torn tail costs exactly the final rows
    for f in range(inputs.shape[0]):
        w.append_rows(f, inputs[f : f + 1], statuses[f : f + 1])
    w.close()
    return w


def test_empty_journal_yields_no_examples(tmp_path):
    # a directory with no segments at all: nothing to train on, and the
    # missing identity META is a typed refusal, not a zero-wide model
    empty = tmp_path / "empty"
    empty.mkdir()
    ds = JournalDataset(str(empty), seed=0)
    assert len(ds) == 0 and ds.meta()["frames"] == 0
    assert list(ds.shards()) == []
    with pytest.raises(ValueError, match="identity META"):
        train_from_journal([str(empty)], seed=0)
    # a journal holding only its META record (writer opened, no rows):
    # discovered, zero frames, zero examples — but identity known
    bare = tmp_path / "bare"
    JournalWriter(str(bare), meta={"num_players": 2, "input_size": 1}).close()
    model, watermark = train_from_journal([str(bare)], seed=0)
    assert watermark["frames"] == 0
    assert float(model.tables.support.sum()) == 0.0
    assert model.num_players == 2


def test_torn_tail_truncates_extraction(tmp_path):
    """A torn final record (host died mid-write) silently truncates the
    dataset to the durable prefix — same rows recovery would replay."""
    inputs, statuses = _toggle_inputs(hold=4, cycles=6)
    path = str(tmp_path / "torn")
    _write_journal(path, inputs, statuses)
    whole = scan_journal(path, repair=False)
    assert whole.frames == inputs.shape[0]
    # tear the tail: chop a few bytes off the last segment mid-record
    segs = sorted(
        f for f in os.listdir(path) if f.endswith(".wal")
    )
    last = os.path.join(path, segs[-1])
    with open(last, "r+b") as f:
        f.truncate(os.path.getsize(last) - 5)
    ds = JournalDataset(path, seed=0)
    assert ds.meta()["frames"] == inputs.shape[0] - 1
    (ex,) = list(ds.shards(shuffle=False))
    ref = extract_examples(inputs[:-1], statuses[:-1])
    for k in ("run", "switched", "src", "dst", "valid"):
        np.testing.assert_array_equal(ex[k], ref[k], err_msg=k)


def test_mid_rotation_boundary_parity(tmp_path):
    """Rows spread across many rotated segments extract byte-identically
    to the same rows in one segment — rotation is invisible to the
    dataset."""
    inputs, statuses = _toggle_inputs(hold=5, cycles=10)
    one = str(tmp_path / "one")
    many = str(tmp_path / "many")
    _write_journal(one, inputs, statuses)
    w = _write_journal(many, inputs, statuses, segment_bytes=128)
    assert w.rotations > 2  # the boundary case actually occurred
    ex_one = list(JournalDataset(one, seed=0).shards(shuffle=False))
    ex_many = list(JournalDataset(many, seed=0).shards(shuffle=False))
    assert len(ex_one) == len(ex_many) == 1
    for k in ("run", "switched", "src", "dst", "valid"):
        np.testing.assert_array_equal(ex_one[0][k], ex_many[0][k],
                                      err_msg=k)


# ----------------------------------------------------------------------
# the trained model: drop-in InputHistoryModel surface
# ----------------------------------------------------------------------


def _trained_toggle_model(hold=6, cycles=12):
    inputs, statuses = _toggle_inputs(hold=hold, cycles=cycles)
    ex = extract_examples(inputs, statuses)
    return train_on_examples([ex], num_players=1, input_size=1)


def test_array_model_learns_hazard_and_transitions():
    m = _trained_toggle_model(hold=6)
    st = m._stats[0]
    assert st.n_holds() >= 8
    # the hazard spikes at the true hold length and stays low before it
    assert st.hazard(6) > 0.7
    assert st.hazard(3) < 0.2
    assert st.next_values(bytes([5]))[0][0] == bytes([9])
    assert st.next_values(bytes([9]))[0][0] == bytes([5])
    # the inherited rank_branches runs unchanged against the table views
    preds = m.rank_branches(
        [(99, bytes([5]), 4)], anchor_frame=98, rollout=8, limit=6
    )
    assert preds and preds[0][:2] == (0, 4) and preds[0][2][0] == 9
    # clones share the frozen tables; run trackers are per-clone
    c = m.clone()
    assert c.tables is m.tables
    c.observe(0, bytes([5]))
    assert c._stats[0].cur_len == 1 and m._stats[0].cur_len == 0


def test_array_model_serialization_round_trip_and_typed_errors():
    m = _trained_toggle_model()
    blob = m.to_bytes()
    m2 = ArrayInputModel.from_bytes(blob)
    assert m2.to_bytes() == blob  # byte-stable round trip
    with pytest.raises(ModelIncompatible):
        ArrayInputModel.from_bytes(b"NOTMODEL" + blob[8:])
    with pytest.raises(ModelIncompatible):
        ArrayInputModel.from_bytes(blob[:-16])  # truncated mid-array
    # run-tracker state only loads into the same version (tables travel
    # by registry version, not by ticket)
    other = ArrayInputModel(m.tables, version=m.version + 1)
    with pytest.raises(ModelIncompatible):
        other.load_state_dict(m.state_dict())


def test_registry_round_trip_and_typed_errors(tmp_path):
    m = _trained_toggle_model()
    reg = ModelRegistry(str(tmp_path / "reg"))
    with pytest.raises(ModelIncompatible):
        reg.load()  # empty registry
    v1 = reg.publish(m, watermark={"frames": 72})
    assert v1 == 1 and reg.latest() == 1
    loaded = reg.load(v1)
    assert loaded.to_bytes() == m.to_bytes()
    assert reg.entry(v1)["watermark"]["frames"] == 72
    with pytest.raises(ModelIncompatible):
        reg.load(99)  # absent version
    # game-identity gate: a 1-player model must not load for a 2-player
    # game
    with pytest.raises(ModelIncompatible):
        reg.load(v1, game=ExGame(num_players=2, num_entities=ENTITIES))
    # a corrupt blob is caught by the manifest checksum, typed
    blob_path = os.path.join(str(tmp_path / "reg"), reg.entry(v1)["file"])
    with open(blob_path, "r+b") as f:
        f.seek(32)
        b = f.read(1)
        f.seek(32)
        f.write(bytes([b[0] ^ 0x40]))
    with pytest.raises(ModelIncompatible):
        ModelRegistry(str(tmp_path / "reg")).load(v1)


# ----------------------------------------------------------------------
# the end-to-end loop: journal -> train -> registry -> hot-swap
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_registry(tmp_path_factory):
    """Journal THE seeded starved traffic shape (single-device fleet),
    train on the WAL, publish — shared by the loop tests below. Returns
    (registry, version, journal_dir)."""
    tmp = tmp_path_factory.mktemp("learn_loop")
    journal_dir = str(tmp / "journal")
    host, keys = run_starved(
        held_scripts, speculation=False, journal_dir=journal_dir,
        seed=SEED, ticks=TICKS,
    )
    for k in keys:
        host.detach(k)  # final-drain + close every lane's writer
    # num_players pinned to the HOST width: the fleet mixes 2/3/4-player
    # matches and the model must be as wide as the host installing it
    model, watermark = train_from_journal(
        [journal_dir], seed=SEED, num_players=4,
    )
    assert float(model.tables.support.sum()) > 0
    assert watermark["frames"] > 0
    reg = ModelRegistry(str(tmp / "registry"))
    version = reg.publish(
        model, game=ExGame(num_players=4, num_entities=ENTITIES),
        watermark=watermark,
    )
    return reg, version, journal_dir


def run_starved_with_install(model, *, install_tick, mesh=None,
                             journal_dir=None, sessions=4, ticks=TICKS,
                             hole_every=30, hole_len=12, seed=SEED):
    """run_starved's exact traffic (same seeds, same starvation holes),
    speculating, with `model` hot-swapped in at the `install_tick` tick
    boundary MID-drive — the serve is live across the swap."""
    clock = FakeClock()
    net = InMemoryNetwork(
        clock, latency_ms=16, jitter_ms=4, loss=0.0, seed=seed
    )
    host = SessionHost(
        ExGame(num_players=4, num_entities=ENTITIES),
        max_prediction=8, num_players=4, max_sessions=sessions + 4,
        clock=clock, idle_timeout_ms=0, speculation=True, mesh=mesh,
        journal_dir=journal_dir,
    )
    matches = build_matches(host, net, clock, sessions=sessions, seed=seed)
    sync_fleet(host, matches, clock)
    scripts = held_scripts(matches, ticks, seed)
    starve = starve_on_tick(
        net, matches, hole_every=hole_every, hole_len=hole_len
    )

    def on_tick(t):
        if t == install_tick:
            host.install_input_model(model)
        starve(t)

    drive_scripted(host, matches, clock, scripts, ticks, on_tick=on_tick)
    host.device.block_until_ready()
    return host, [k for keys in matches for k in keys]


def test_learning_loop_end_to_end_single_device(fleet_registry):
    """The acceptance loop: the registry-loaded trained model installs
    into a live speculating host at a tick boundary before the first
    starvation hole; on the same seeded starved traffic its hit rate
    meets or beats the online Counter model's, and the host stays
    bitwise identical to a never-speculating twin across the swap."""
    reg, version, _ = fleet_registry
    game = ExGame(num_players=4, num_entities=ENTITIES)
    loaded = reg.load(version, game=game)

    host_online, _ = run_starved(
        held_scripts, speculation=True, seed=SEED, ticks=TICKS,
    )
    online_rate = host_online.spec_hit_rate
    assert host_online.frames_served_from_speculation > 0

    host_tr, keys_tr = run_starved_with_install(loaded, install_tick=10)
    assert host_tr.input_model_version == version
    sec = host_tr._spec.section()
    assert sec["model_version"] == version and sec["model_swaps"] == 1
    assert host_tr.frames_served_from_speculation > 0
    # trained on exactly this traffic: the fleet-wide statistics must
    # serve at least as well as the in-match online Counter
    assert host_tr.spec_hit_rate >= online_rate > 0.0, (
        f"trained {host_tr.spec_hit_rate} < online {online_rate}"
    )

    host_off, keys_off = run_starved(
        held_scripts, speculation=False, seed=SEED, ticks=TICKS,
    )
    assert_bitwise_twin(host_tr, keys_tr, host_off, keys_off)


def test_learning_loop_sharded_swap_parity(fleet_registry, tmp_path):
    """The sharded arm: the trained model hot-swaps into a session-mesh
    host mid-serve and the sharded speculating fleet stays bit-identical
    to the single-device never-speculating twin. The run also journals —
    its WAL must extract byte-identical example tensors to the
    single-device fixture journal of the same seeded traffic (the
    determinism claim that lets a mixed fleet pool its journals)."""
    from ggrs_tpu.parallel.mesh import make_session_mesh

    reg, version, single_journal = fleet_registry
    loaded = reg.load(version)
    sharded_journal = str(tmp_path / "sharded_journal")
    host_on, keys_on = run_starved_with_install(
        loaded, install_tick=10, mesh=make_session_mesh(8),
        journal_dir=sharded_journal,
    )
    assert host_on.frames_served_from_speculation > 0
    assert host_on.input_model_version == version

    host_off, keys_off = run_starved(
        held_scripts, speculation=False, seed=SEED, ticks=TICKS,
    )
    # parity first (the journal taps drain at detach inside the check's
    # host accessors, so assert before closing lanes)
    assert_bitwise_twin(host_on, keys_on, host_off, keys_off)

    # sharded-vs-single-device byte parity of the extracted examples
    for k in keys_on:
        host_on.detach(k)
    ds_single = JournalDataset(single_journal, seed=0)
    ds_sharded = JournalDataset(sharded_journal, seed=0)
    assert len(ds_single) == len(ds_sharded) > 0
    singles = list(ds_single.shards(shuffle=False))
    shardeds = list(ds_sharded.shards(shuffle=False))
    for ea, eb in zip(singles, shardeds):
        assert os.path.basename(ea["path"]) == os.path.basename(eb["path"])
        assert ea["frames"] == eb["frames"]
        for k in ("run", "switched", "src", "dst", "valid"):
            np.testing.assert_array_equal(
                ea[k], eb[k],
                err_msg=f"{os.path.basename(ea['path'])}:{k}",
            )
