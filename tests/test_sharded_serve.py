"""Sharded serving: the SessionHost megabatch GSPMD-partitioned over a
`session` device mesh (ShardedMultiSessionDeviceCore) on the conftest's
8 virtual CPU devices.

The correctness contract is the bitwise one the repo already enforces
everywhere: a sharded host/env must produce bit-identical per-slot
device state, ring bytes and checksum histories to a single-device twin
fed the same traffic — checkpoints and migration payloads stay CANONICAL
(logical slot order), so the two layouts interoperate freely."""

import numpy as np
import pytest

import jax

from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.obs import GLOBAL_TELEMETRY
from ggrs_tpu.parallel.mesh import make_session_mesh
from ggrs_tpu.serve import SessionHost, migrate_session
from ggrs_tpu.tpu.backend import (
    MultiSessionDeviceCore,
    ShardedMultiSessionDeviceCore,
)
from ggrs_tpu.utils.clock import FakeClock

ENTITIES = 16
FRAME_MS = 16


@pytest.fixture(scope="module")
def mesh():
    return make_session_mesh(8)  # 8-wide session axis, no entity split


def _assert_tree_equal(ta, tb, what):
    la = jax.tree_util.tree_leaves_with_path(ta)
    lb = jax.tree_util.tree_leaves(tb)
    assert len(la) == len(lb)
    for (path, a), b in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"{what}{jax.tree_util.keystr(path)}",
        )


def build_fleet(mesh, *, seed=13, sessions=8, ticks=40, loss=0.03,
                **host_kw):
    from ggrs_tpu.serve.loadgen import (
        build_matches,
        drive_scripted,
        make_scripts,
        sync_fleet,
    )

    clock = FakeClock()
    net = InMemoryNetwork(
        clock, latency_ms=20, jitter_ms=8, loss=loss, seed=seed
    )
    host = SessionHost(
        ExGame(num_players=4, num_entities=ENTITIES),
        max_prediction=8, num_players=4, max_sessions=sessions + 4,
        clock=clock, idle_timeout_ms=0, mesh=mesh, **host_kw,
    )
    matches = build_matches(host, net, clock, sessions=sessions, seed=seed)
    sync_fleet(host, matches, clock)
    scripts = make_scripts(matches, ticks, seed=seed)
    desyncs = drive_scripted(host, matches, clock, scripts, ticks)
    assert not desyncs, f"lossy fleet desynced (mesh={mesh is not None})"
    host.device.block_until_ready()
    return host, [k for keys in matches for k in keys]


# ----------------------------------------------------------------------
# hosted fleet bitwise parity vs the single-device twin
# ----------------------------------------------------------------------


def test_sharded_host_fleet_bitwise_parity(mesh):
    """A lossy 8-session hosted fleet on the 8-shard session mesh vs a
    single-device twin fed identical traffic: every session's checksum
    history, the canonical stacked state AND ring bytes, and the
    explicit cross-shard checksum pass all bit-match — and the fleet
    actually spread across shards (slot->shard affinity)."""
    host_s, keys_s = build_fleet(mesh)
    host_p, keys_p = build_fleet(None)
    assert isinstance(host_s.device, ShardedMultiSessionDeviceCore)
    assert type(host_p.device) is MultiSessionDeviceCore
    for ka, kb in zip(keys_s, keys_p):
        sa, sb = host_s.session(ka), host_p.session(kb)
        assert sa.current_frame == sb.current_frame > 0
        assert sa.local_checksum_history == sb.local_checksum_history
        assert len(sa.local_checksum_history) > 0  # non-vacuous
    rs, ss = host_s.device.stacked_canonical()
    rp, sp = host_p.device.stacked_canonical()
    _assert_tree_equal(rs, rp, "rings")
    _assert_tree_equal(ss, sp, "states")
    hi_s, lo_s = host_s.device.checksum_slots()
    hi_p, lo_p = host_p.device.checksum_slots()
    np.testing.assert_array_equal(hi_s, hi_p)
    np.testing.assert_array_equal(lo_s, lo_p)
    # admission affinity spread the 8 sessions over all 8 shards
    shards = {
        host_s.device.shard_of(host_s._lanes[k].slot) for k in keys_s
    }
    assert len(shards) == 8


def test_sharded_slot_layout_round_trip(mesh, tmp_path):
    """The interleaved logical->physical slot map is a bijection onto
    the non-dummy stack rows, shard_of matches the physical placement,
    and checkpoints round-trip ACROSS layouts bit-exactly."""
    game = ExGame(num_players=2, num_entities=ENTITIES)
    core = ShardedMultiSessionDeviceCore(game, 8, 2, 10, mesh=mesh)
    assert core.stack_slots % core.session_shards == 0
    assert len(set(core._phys.tolist())) == core.capacity
    per = core._per_shard
    for slot in range(core.capacity):
        phys = int(core._phys[slot])
        assert core.shard_of(slot) == phys // per
        assert int(core._phys_inverse[phys]) == slot
    assert int(core._phys_inverse[core.pad_slot]) == core.capacity
    # write something slot-distinct, round-trip through a checkpoint
    # onto the OTHER layout and back
    rows = np.tile(core.core.pad_tick_row(), (core.capacity, 1))
    rows[:, 2] = 1
    rows[:, core.core._off_input] = np.arange(core.capacity) % 16
    core.dispatch_rows(
        np.arange(core.capacity, dtype=np.int32), rows, fast=True
    )
    path = str(tmp_path / "ggrs_sharded_roundtrip.npz")
    core.save(path)
    plain = MultiSessionDeviceCore.restore(path, game)
    back = MultiSessionDeviceCore.restore(path, game, mesh=mesh)
    assert isinstance(back, ShardedMultiSessionDeviceCore)
    for a, b in zip(plain.stacked_canonical(), back.stacked_canonical()):
        _assert_tree_equal(a, b, "roundtrip")
    for slot in (0, core.capacity - 1):
        _assert_tree_equal(
            core.state_numpy(slot), plain.state_numpy(slot), f"slot{slot}"
        )


# ----------------------------------------------------------------------
# migration across a sharded <-> unsharded host pair
# ----------------------------------------------------------------------


def test_migration_across_sharded_and_unsharded_hosts(mesh):
    """A live mid-match migration from a SHARDED host to a single-device
    host (export_slot -> import_slot through the canonical per-slot
    payload), peers none the wiser, then back again — checksum exchange
    keeps running across both handoffs and the final world bit-matches
    an undisturbed twin match."""
    import random

    from ggrs_tpu import PlayerType, SessionBuilder, SessionState
    from ggrs_tpu.types import DesyncDetection

    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=20, jitter_ms=0, loss=0.0)

    def peer(addr, other, handle, seed):
        return (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(8)
            .with_input_delay(1)
            .with_desync_detection_mode(DesyncDetection.on(interval=10))
            .with_clock(clock)
            .with_rng(random.Random(seed * 131 + handle + 7))
            .add_player(PlayerType.local(), handle)
            .add_player(PlayerType.remote(other), 1 - handle)
            .start_p2p_session(net.socket(addr))
        )

    def make_host(m):
        return SessionHost(
            ExGame(num_players=2, num_entities=ENTITIES),
            max_prediction=8, num_players=2, max_sessions=6,
            clock=clock, idle_timeout_ms=0, mesh=m,
        )

    h_shard, h_plain = make_host(mesh), make_host(None)
    a0 = peer("a0", "a1", 0, seed=1)
    a1 = peer("a1", "a0", 1, seed=2)
    b0 = peer("b0", "b1", 0, seed=3)  # undisturbed twin match
    b1 = peer("b1", "b0", 1, seed=4)
    ka0, ka1 = h_shard.attach(a0), h_shard.attach(a1)
    kb0, kb1 = h_shard.attach(b0), h_shard.attach(b1)

    for _ in range(600):
        h_shard.tick()
        h_plain.tick()
        clock.advance(FRAME_MS)
        if all(
            s.current_state() == SessionState.RUNNING
            for s in (a0, a1, b0, b1)
        ):
            break
    assert a0.current_state() == SessionState.RUNNING

    script = lambda h, t: (t * 3 + h * 5 + 1) % 16  # noqa: E731
    desyncs = []
    keymap = [
        (a0, [h_shard, ka0], 0), (a1, [h_shard, ka1], 1),
        (b0, [h_shard, kb0], 0), (b1, [h_shard, kb1], 1),
    ]

    def drive(t):
        for _sess, (host, key), h in keymap:
            host.submit_input(key, h, bytes([script(h, t)]))
        for host in (h_shard, h_plain):
            for _key, evs in host.tick().items():
                desyncs.extend(
                    e for e in evs if type(e).__name__ == "DesyncDetected"
                )
        clock.advance(FRAME_MS)

    for t in range(20):
        drive(t)
    # sharded -> single-device, mid-match
    k_on_plain = migrate_session(h_shard, h_plain, ka0)
    keymap[0][1][:] = [h_plain, k_on_plain]
    for t in range(20, 50):
        drive(t)
    # ...and back onto the mesh
    k_back = migrate_session(h_plain, h_shard, k_on_plain)
    keymap[0][1][:] = [h_shard, k_back]
    for t in range(50, 80):
        drive(t)

    assert not desyncs, f"cross-layout migration desynced: {desyncs[:3]}"
    assert a0.current_frame == b0.current_frame > 40
    common = set(a0.local_checksum_history) & set(b0.local_checksum_history)
    assert common, "no comparable frames published"
    for f in common:
        assert a0.local_checksum_history[f] == b0.local_checksum_history[f]
    migrated = h_shard.device.state_numpy(h_shard._lanes[k_back].slot)
    twin = h_shard.device.state_numpy(h_shard._lanes[kb0].slot)
    _assert_tree_equal(migrated, twin, "migrated-vs-twin")


# ----------------------------------------------------------------------
# sharded env: masked auto-reset parity
# ----------------------------------------------------------------------


def test_sharded_env_masked_auto_reset_parity(mesh):
    """A sharded standalone RollbackEnv vs a single-device twin through
    episode boundaries (auto-reset = the masked batch reset on-mesh):
    per-step checksums, rewards and done flags bit-match; a PARTIAL
    reset mask (arbitrary slots) also bit-matches across layouts."""
    from ggrs_tpu.env import (
        InputModelOpponent,
        RollbackEnv,
        held_value_trace,
    )

    trace = held_value_trace([1, 4, 2, 8, 1, 4, 2, 8, 5, 4])

    def build(m):
        return RollbackEnv(
            ExGame(num_players=2, num_entities=ENTITIES),
            num_envs=16,
            opponents={1: InputModelOpponent(trace, seed=9)},
            episode_len=6,
            mesh=m,
        )

    es, ep = build(mesh), build(None)
    assert isinstance(es._device, ShardedMultiSessionDeviceCore)
    es.reset()
    ep.reset()
    for t in range(14):  # crosses the episode_len=6 boundary twice
        a = np.full((16, 1), (t * 3 + 1) % 16, np.uint8)
        _, rs, ds, _ = es.step(a)
        _, rp, dp, _ = ep.step(a)
        assert es.checksums() == ep.checksums(), f"step {t}"
        np.testing.assert_array_equal(ds, dp)
        np.testing.assert_array_equal(np.asarray(rs), np.asarray(rp))
    assert es.episodes_total == ep.episodes_total >= 32
    # partial masked reset, arbitrary slot pattern, both layouts
    mask = np.zeros((16,), dtype=bool)
    mask[[1, 4, 7, 10, 15]] = True
    es._device.reset_slots_masked(mask)
    ep._device.reset_slots_masked(mask)
    assert es.checksums() == ep.checksums()
    for tree_s, tree_p in zip(
        es._device.stacked_canonical(), ep._device.stacked_canonical()
    ):
        _assert_tree_equal(tree_s, tree_p, "post-partial-reset")


# ----------------------------------------------------------------------
# jit-cache budget under the sanitizer
# ----------------------------------------------------------------------


def test_sharded_jit_cache_budget_under_sanitizer(mesh):
    """GGRS_SANITIZE semantics on the sharded core: warmup compiles the
    whole (row-bucket x depth-bucket) grid on-mesh, the lossy serve
    afterwards compiles NOTHING, and the megabatch jit cache stays
    within dispatch_bucket_budget()."""
    from ggrs_tpu.analysis.sanitize import (
        install_sanitizer,
        uninstall_sanitizer,
    )

    san = install_sanitizer()
    try:
        host, keys = build_fleet(mesh, sessions=6, ticks=25, warmup=True)
        assert not san.recompiles, (
            "post-warmup recompile on the sharded host:\n"
            + "\n".join(e.render() for e in san.recompiles)
        )
        dev = host.device
        cache = (
            dev._dispatch_fn._cache_size()
            + dev._dispatch_fast_fn._cache_size()
        )
        assert cache <= dev.dispatch_bucket_budget()
        assert dev.megabatches > 0
    finally:
        uninstall_sanitizer()


# ----------------------------------------------------------------------
# lossy soak: zero desyncs + shard instruments
# ----------------------------------------------------------------------


def test_sharded_lossy_soak_zero_desyncs(mesh):
    """A lossier, longer soak on the sharded host: zero desyncs (real
    checksum comparisons — desync detection is on in every match), rows
    actually coalesced, and the shard instruments
    (ggrs_shard_rows{shard=} + ggrs_shard_imbalance) populated through
    the registry-driven exporters."""
    from ggrs_tpu import enable_global_telemetry

    enable_global_telemetry()
    try:
        host, keys = build_fleet(
            mesh, seed=5, sessions=10, ticks=60, loss=0.08
        )
        dev = host.device
        assert dev.megabatches > 0
        assert dev.rows_dispatched / dev.megabatches > 1.0
        snap = host.telemetry()
        assert snap["host"]["desyncs_observed"] == 0
        assert snap["host"]["session_shards"] == 8
        rows_metric = snap["metrics"]["ggrs_shard_rows"]
        assert rows_metric["type"] == "gauge" and rows_metric["values"]
        imb = snap["metrics"]["ggrs_shard_imbalance"]
        assert next(iter(imb["values"].values()))["count"] > 0
        prom = GLOBAL_TELEMETRY.prometheus()
        assert "ggrs_shard_rows{" in prom
        assert "ggrs_shard_imbalance" in prom
    finally:
        GLOBAL_TELEMETRY.enabled = False
        GLOBAL_TELEMETRY.reset()
