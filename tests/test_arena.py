"""Second model family (ggrs_tpu/models/arena.py) through the whole stack:
oracle/device bit-parity, rollback backend, fused SyncTest, the beam, and
entity-sharded execution where the per-team centroid reduction becomes a
real cross-shard collective. The framework layers are game-agnostic; these
tests are the proof by second witness.
"""

import numpy as np
import pytest

from ggrs_tpu import SessionBuilder
from ggrs_tpu.models import arena

PLAYERS = 2
ENTITIES = 128


def script(frames, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 64, size=(frames, PLAYERS, 1), dtype=np.uint8)


def test_device_step_matches_oracle_bit_for_bit():
    import jax

    game = arena.Arena(PLAYERS, ENTITIES)
    dev = game.init_state()
    host = arena.init_oracle(PLAYERS, ENTITIES)
    step = jax.jit(game.step)
    statuses = np.zeros(PLAYERS, dtype=np.int32)
    inputs = script(60, seed=1)
    for f in range(60):
        dev = step(dev, inputs[f], statuses)
        host = arena.step_oracle(host, inputs[f].reshape(-1), statuses, PLAYERS)
    for k in host:
        assert np.array_equal(np.asarray(dev[k]), host[k]), f"{k} diverged"
    dhi, dlo = game.checksum(dev)
    ohi, olo = arena.checksum_oracle(host)
    assert (int(dhi), int(dlo)) == (ohi, olo)


def test_gameplay_semantics():
    """Combat near the enemy centroid drains hp; overdrive drains energy;
    the torus wraps."""
    host = arena.init_oracle(PLAYERS, ENTITIES)
    statuses = np.zeros(PLAYERS, dtype=np.int32)
    rally_all = np.full((PLAYERS, 1), arena.INPUT_RALLY, dtype=np.uint8)
    for _ in range(200):
        host = arena.step_oracle(host, rally_all.reshape(-1), statuses, PLAYERS)
    # teams are interleaved on the spawn grid, so rallying pulls everyone
    # into overlapping blobs: combat must have happened
    assert host["hp"].min() < arena.HP_INIT
    assert (host["pos"] >= 0).all() and (host["pos"] <= arena.ARENA_MASK).all()

    over = np.full((PLAYERS, 1), arena.INPUT_OVERDRIVE | arena.INPUT_RIGHT, np.uint8)
    host2 = arena.init_oracle(PLAYERS, ENTITIES)
    for _ in range(10):
        host2 = arena.step_oracle(host2, over.reshape(-1), statuses, PLAYERS)
    assert host2["energy"].max() < arena.ENERGY_INIT


def test_extinct_team_projects_no_combat():
    """Regression: a team with zero living entities must not leave a
    phantom centroid at the origin damaging enemies near (0,0)."""
    host = arena.init_oracle(PLAYERS, 8)
    host["hp"][1::2] = 0  # team 1 extinct
    host["pos"][:] = 0  # everyone parked at the origin
    statuses = np.zeros(PLAYERS, dtype=np.int32)
    idle = np.zeros(PLAYERS, dtype=np.uint8)
    for _ in range(5):
        host = arena.step_oracle(host, idle, statuses, PLAYERS)
    assert (host["hp"][0::2] == arena.HP_INIT).all(), "phantom combat damage"


def test_rollback_backend_synctest_with_arena():
    from ggrs_tpu.tpu import TpuRollbackBackend

    backend = TpuRollbackBackend(
        arena.Arena(PLAYERS, ENTITIES), max_prediction=6, num_players=PLAYERS
    )
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(6)
        .with_check_distance(4)
        .start_synctest_session()
    )
    inputs = script(40, seed=3)
    for f in range(40):
        for h in range(PLAYERS):
            sess.add_local_input(h, bytes(inputs[f, h]))
        backend.handle_requests(sess.advance_frame())

    # resimulated end state equals the straight-line oracle
    host = arena.init_oracle(PLAYERS, ENTITIES)
    statuses = np.zeros(PLAYERS, dtype=np.int32)
    for f in range(40):
        host = arena.step_oracle(host, inputs[f].reshape(-1), statuses, PLAYERS)
    dev = backend.state_numpy()
    for k in host:
        assert np.array_equal(np.asarray(dev[k]), host[k]), f"{k} diverged"


def test_fused_synctest_session_with_arena():
    from ggrs_tpu.tpu import TpuSyncTestSession

    sess = TpuSyncTestSession(
        arena.Arena(PLAYERS, ENTITIES), num_players=PLAYERS, check_distance=4
    )
    sess.advance_frames(script(40, seed=5))
    sess.check()


def test_beam_backend_with_arena_matches_plain():
    """Beam adoption is bit-identical for the second model too (its step
    branches on statuses only for the disconnect-coast, so speculated
    CONFIRMED trajectories are valid)."""
    from ggrs_tpu.tpu import TpuRollbackBackend

    def drive(beam_width):
        backend = TpuRollbackBackend(
            arena.Arena(PLAYERS, ENTITIES), max_prediction=6,
            num_players=PLAYERS, beam_width=beam_width,
        )
        sess = (
            SessionBuilder(input_size=1)
            .with_num_players(PLAYERS)
            .with_max_prediction_window(6)
            .with_check_distance(4)
            .start_synctest_session()
        )
        for f in range(30):
            for h in range(PLAYERS):
                sess.add_local_input(h, bytes([arena.INPUT_RIGHT]))  # constant
            backend.handle_requests(sess.advance_frame())
        return backend

    beam, plain = drive(8), drive(0)
    assert beam.beam_hits > 10
    sb, sp = beam.state_numpy(), plain.state_numpy()
    for k in sb:
        assert np.array_equal(np.asarray(sb[k]), np.asarray(sp[k]))


def test_sharded_arena_psum_checksum_matches_oracle():
    """The explicit shard_map+psum desync checksum works for the second
    model's key order too (pos|vel|hp|energy|frame)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from ggrs_tpu.parallel.mesh import make_mesh
    from ggrs_tpu.parallel.sharded import shard_state, sharded_checksum

    mesh = make_mesh(8)
    entities = 256
    host = arena.init_oracle(PLAYERS, entities)
    sharded = shard_state(jax.device_put(host), mesh)
    hi, lo = sharded_checksum(sharded, mesh, keys=arena.Arena.checksum_keys)
    ohi, olo = arena.checksum_oracle(host)
    assert (int(hi), int(lo)) == (ohi, olo)


def test_sharded_arena_centroid_collective_matches_oracle():
    """Entity-sharded arena step: the per-team centroid reduction crosses
    shards (GSPMD inserts the collective); results stay bit-identical to
    the unsharded oracle."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ggrs_tpu.parallel.mesh import make_mesh
    from ggrs_tpu.parallel.sharded import shard_state

    mesh = make_mesh(8)
    entities = 256  # divisible by the 4-way entity axis
    game = arena.Arena(PLAYERS, entities)
    host = arena.init_oracle(PLAYERS, entities)
    state = shard_state(jax.device_put(host), mesh)

    @jax.jit
    def step(s, inputs, statuses):
        out = game.step(s, inputs, statuses)
        # keep the state entity-sharded across steps
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P("entity") if x.ndim >= 1 else P())
            ),
            out,
        )

    statuses = np.zeros(PLAYERS, dtype=np.int32)
    inputs = script(25, seed=9)
    for f in range(25):
        state = step(state, inputs[f], statuses)
        host = arena.step_oracle(host, inputs[f].reshape(-1), statuses, PLAYERS)
    for k in host:
        assert np.array_equal(np.asarray(state[k]), host[k]), f"{k} diverged"
