"""Sparse-saving mode and P2P-over-device-backend integration.

The decisive cross-implementation test: one peer fulfills requests with the
fused TPU backend, the other with the numpy oracle, desync detection on —
the two implementations must produce identical checksums for every confirmed
frame or the framework's own desync detector convicts them.
"""

import random

import numpy as np
import pytest

from ggrs_tpu import (
    AdvanceFrame,
    DesyncDetected,
    DesyncDetection,
    LoadGameState,
    PlayerType,
    SaveGameState,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.models import ex_game
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.ops.fixed_point import combine_checksum
from ggrs_tpu.utils.clock import FakeClock
from stubs import GameStub

NUM_PLAYERS = 2
ENTITIES = 128


def build_pair(clock, net, *, sparse=False, desync=None, max_prediction=8):
    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(max_prediction)
            .with_sparse_saving_mode(sparse)
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        if desync is not None:
            b = b.with_desync_detection_mode(desync)
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        return b.start_p2p_session(net.socket(my_addr))

    return build("a", "b", 0), build("b", "a", 1)


def sync_sessions(sessions, clock):
    for _ in range(400):
        for s in sessions:
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(s.current_state() == SessionState.RUNNING for s in sessions):
            return
    raise AssertionError("sessions failed to synchronize")


class OracleRunner:
    def __init__(self):
        self.state = ex_game.init_oracle(NUM_PLAYERS, ENTITIES)

    def handle_requests(self, requests):
        for req in requests:
            if isinstance(req, SaveGameState):
                req.cell.save(
                    req.frame,
                    {k: np.copy(v) for k, v in self.state.items()},
                    combine_checksum(*ex_game.checksum_oracle(self.state)),
                )
            elif isinstance(req, LoadGameState):
                self.state = {k: np.copy(v) for k, v in req.cell.load().items()}
            elif isinstance(req, AdvanceFrame):
                inputs = np.array([b[0] for b, _ in req.inputs], dtype=np.uint8)
                statuses = np.array([int(s) for _, s in req.inputs], dtype=np.int32)
                self.state = ex_game.step_oracle(
                    self.state, inputs, statuses, NUM_PLAYERS
                )


@pytest.mark.parametrize("sparse", [False, True])
def test_sparse_saving_replicas_converge(sparse):
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=50, jitter_ms=20, seed=8)
    s1, s2 = build_pair(clock, net, sparse=sparse)
    sync_sessions([s1, s2], clock)
    g1, g2 = GameStub(), GameStub()

    for frame in range(80):
        s1.add_local_input(0, bytes([(frame * 3 + 1) % 16]))
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, bytes([(frame * 5 + 2) % 16]))
        g2.handle_requests(s2.advance_frame())
        s1.events()
        s2.events()
        clock.advance(16)

    for _ in range(10):
        s1.poll_remote_clients()
        s2.poll_remote_clients()
        clock.advance(16)
    s1.add_local_input(0, b"\x00")
    g1.handle_requests(s1.advance_frame())
    s2.add_local_input(1, b"\x00")
    g2.handle_requests(s2.advance_frame())

    confirmed = min(s1.confirmed_frame(), s2.confirmed_frame())
    assert confirmed > 40
    for f in range(1, confirmed + 1):
        assert g1.history[f] == g2.history[f], f"replicas diverged at frame {f}"
    if sparse:
        # sparse saving must actually save less often than every frame
        assert len(g1.saved_frames) < s1.current_frame


def test_device_backend_peer_vs_host_oracle_peer_no_desync():
    """Device-backend peer and host-oracle peer exchange checksum reports:
    bit-exact agreement or DesyncDetected convicts the device path."""
    from ggrs_tpu.tpu import TpuRollbackBackend

    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=30, jitter_ms=10, seed=21)
    s1, s2 = build_pair(clock, net, desync=DesyncDetection.on(10))
    sync_sessions([s1, s2], clock)

    backend = TpuRollbackBackend(
        ex_game.ExGame(NUM_PLAYERS, ENTITIES), max_prediction=8, num_players=NUM_PLAYERS
    )
    oracle = OracleRunner()

    events = []
    for frame in range(150):
        s1.add_local_input(0, bytes([(frame * 7 + 1) % 16]))
        backend.handle_requests(s1.advance_frame())
        s2.add_local_input(1, bytes([(frame * 11 + 2) % 16]))
        oracle.handle_requests(s2.advance_frame())
        events += s1.events() + s2.events()
        clock.advance(16)

    desyncs = [e for e in events if isinstance(e, DesyncDetected)]
    assert not desyncs, f"device vs host checksum mismatch: {desyncs[:3]}"
    # sanity: checksum reports actually flowed
    assert s1.local_checksum_history and s2.local_checksum_history

    # and the two replicas' confirmed states agree bit-for-bit
    confirmed = min(s1.confirmed_frame(), s2.confirmed_frame())
    assert confirmed > 100
    dev = backend.state_numpy()
    assert int(dev["frame"]) == 150
