"""Tracer span aggregation."""

from ggrs_tpu.utils.tracing import Tracer


def test_spans_aggregate_and_nest():
    t = Tracer(enabled=True)
    for _ in range(3):
        with t.span("tick"):
            with t.span("resim"):
                pass
    assert t.stats["tick"].count == 3
    assert t.stats["tick/resim"].count == 3
    assert t.stats["tick"].total_ns >= t.stats["tick/resim"].total_ns
    assert "tick/resim" in t.report()


def test_xprof_annotated_spans_record_normally():
    """xprof mode wraps spans in jax.profiler.TraceAnnotation regions;
    aggregation semantics are unchanged."""
    t = Tracer(enabled=True, xprof=True)
    # the constructor path must actually resolve the annotation class —
    # a None here means spans silently skip xprof region emission
    assert t._annotation_cls is not None
    with t.span("outer"):
        with t.span("inner"):
            pass
    assert t.stats["outer"].count == 1
    assert t.stats["outer/inner"].count == 1


def test_disabled_tracer_records_nothing():
    t = Tracer(enabled=False)
    with t.span("x"):
        pass
    assert not t.stats


def test_report_sizes_name_column_to_longest_path():
    t = Tracer(enabled=True)
    long_name = "session/" + "x" * 60
    with t.span(long_name):
        pass
    with t.span("tick"):
        pass
    lines = t.report().splitlines()
    # the name column sizes to the longest path, so every row's numeric
    # fields start at the same offset — long paths no longer shift them
    name_width = len(long_name)
    count_end = name_width + 1 + 8  # "{name:{w}} {count:>8d}"
    for line in lines:
        assert len(line) > count_end
        field = line[name_width + 1 : count_end].strip()
        assert field in ("count",) or field.isdigit(), (
            f"count column misaligned in {line!r}"
        )
    row = next(l for l in lines if long_name in l)
    assert row.split()[0] == long_name


def test_report_sort_by_total_surfaces_hot_spans_first():
    import time

    t = Tracer(enabled=True)
    with t.span("cold"):
        pass
    with t.span("hot"):
        time.sleep(0.002)
    rows = t.report(sort_by="total").splitlines()[1:]
    assert rows[0].split()[0] == "hot"
    assert rows[1].split()[0] == "cold"
    import pytest

    with pytest.raises(ValueError):
        t.report(sort_by="mean")
