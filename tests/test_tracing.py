"""Tracer span aggregation."""

from ggrs_tpu.utils.tracing import Tracer


def test_spans_aggregate_and_nest():
    t = Tracer(enabled=True)
    for _ in range(3):
        with t.span("tick"):
            with t.span("resim"):
                pass
    assert t.stats["tick"].count == 3
    assert t.stats["tick/resim"].count == 3
    assert t.stats["tick"].total_ns >= t.stats["tick/resim"].total_ns
    assert "tick/resim" in t.report()


def test_xprof_annotated_spans_record_normally():
    """xprof mode wraps spans in jax.profiler.TraceAnnotation regions;
    aggregation semantics are unchanged."""
    t = Tracer(enabled=True, xprof=True)
    # the constructor path must actually resolve the annotation class —
    # a None here means spans silently skip xprof region emission
    assert t._annotation_cls is not None
    with t.span("outer"):
        with t.span("inner"):
            pass
    assert t.stats["outer"].count == 1
    assert t.stats["outer/inner"].count == 1


def test_disabled_tracer_records_nothing():
    t = Tracer(enabled=False)
    with t.span("x"):
        pass
    assert not t.stats
