"""Arena on the pallas fused-SyncTest kernel: full-carry bit parity with the
XLA scan, for both 1-byte and 2-byte (analog throttle) inputs — the witness
that the pallas path is model-generic (VERDICT round 1) and that multi-byte
POD inputs flow through the device paths (reference Input contract,
src/lib.rs:250-255).

Runs the kernel in interpreter mode (tests execute on the CPU mesh); the
real-TPU execution of the same kernel is exercised by bench.py."""

import numpy as np
import pytest

import jax
import jax.tree_util as jtu

from ggrs_tpu.models.arena import Arena, checksum_oracle, init_oracle, step_oracle
from ggrs_tpu.tpu import TpuSyncTestSession

P = 2


def drive(game, backend, script, check_distance, batches=3):
    sess = TpuSyncTestSession(
        game,
        num_players=P,
        check_distance=check_distance,
        flush_interval=10_000,
        backend=backend,
    )
    t = script.shape[0] // batches
    for i in range(batches):
        sess.advance_frames(script[i * t : (i + 1) * t])
    return sess


def assert_carry_equal(a, b):
    la = jtu.tree_leaves_with_path(jax.device_get(a))
    lb = jtu.tree_leaves(jax.device_get(b))
    assert len(la) == len(lb)
    for (path, x), y in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=jtu.keystr(path)
        )


@pytest.mark.parametrize("check_distance,entities", [(2, 256), (6, 512)])
def test_arena_pallas_carry_parity_with_xla(check_distance, entities):
    rng = np.random.default_rng(9)
    script = rng.integers(0, 64, size=(60, P, 1), dtype=np.uint8)
    xla = drive(Arena(P, entities), "xla", script, check_distance)
    pls = drive(Arena(P, entities), "pallas-interpret", script, check_distance)
    assert_carry_equal(xla.carry, pls.carry)
    xla.check()
    pls.check()


def test_arena_wide_inputs_pallas_parity_and_oracle():
    """input_size=2: pallas vs XLA carry parity AND the device state vs a
    straight numpy-oracle replay (ties the whole wide-input path to ground
    truth, including the throttle byte actually changing the dynamics)."""
    rng = np.random.default_rng(10)
    script = np.stack(
        [
            rng.integers(0, 64, size=(48, P), dtype=np.uint8),  # bitmask byte
            rng.integers(0, 16, size=(48, P), dtype=np.uint8),  # throttle byte
        ],
        axis=-1,
    )
    xla = drive(Arena(P, 256, input_size=2), "xla", script, check_distance=4)
    pls = drive(
        Arena(P, 256, input_size=2), "pallas-interpret", script, check_distance=4
    )
    assert_carry_equal(xla.carry, pls.carry)
    pls.check()

    state = init_oracle(P, 256)
    statuses = np.zeros((P,), dtype=np.int32)
    for f in range(48):
        state = step_oracle(state, script[f], statuses, P, input_size=2)
    dev = jax.device_get(pls.carry["state"])
    for key in ("frame", "pos", "vel", "hp", "energy"):
        np.testing.assert_array_equal(np.asarray(dev[key]), state[key])

    # the throttle byte is live: a different throttle script diverges
    alt = script.copy()
    alt[:, :, 1] = (alt[:, :, 1] + 7) % 16
    state2 = init_oracle(P, 256)
    for f in range(48):
        state2 = step_oracle(state2, alt[f], statuses, P, input_size=2)
    assert not np.array_equal(state["pos"], state2["pos"])


def test_wide_input_one_byte_equivalence():
    """Throttle 4 reproduces the 1-byte dynamics exactly (strict-extension
    contract in the model docstring)."""
    rng = np.random.default_rng(11)
    masks = rng.integers(0, 64, size=(30, P), dtype=np.uint8)
    statuses = np.zeros((P,), dtype=np.int32)
    narrow = init_oracle(P, 128)
    wide = init_oracle(P, 128)
    for f in range(30):
        narrow = step_oracle(narrow, masks[f], statuses, P)
        wide_in = np.stack([masks[f], np.full((P,), 4, np.uint8)], axis=-1)
        wide = step_oracle(wide, wide_in, statuses, P, input_size=2)
    for key in narrow:
        np.testing.assert_array_equal(narrow[key], wide[key])


def test_arena_pallas_detects_injected_divergence():
    from ggrs_tpu.errors import MismatchedChecksum

    rng = np.random.default_rng(12)
    script = rng.integers(0, 64, size=(40, P, 1), dtype=np.uint8)
    sess = TpuSyncTestSession(
        Arena(P, 256),
        num_players=P,
        check_distance=4,
        flush_interval=10_000,
        backend="pallas-interpret",
    )
    sess.advance_frames(script[:20])
    sess.check()
    ring = dict(sess.carry["ring"])
    slot = (sess.current_frame - 4) % sess.ring_len
    ring["hp"] = ring["hp"].at[slot, 0].add(1)
    sess.carry = {**sess.carry, "ring": ring}
    sess.advance_frames(script[20:])
    with pytest.raises(MismatchedChecksum):
        sess.check()


def test_unregistered_model_rejected():
    from ggrs_tpu.tpu.pallas_core import get_adapter

    class MysteryGame:
        pass

    with pytest.raises(KeyError):
        get_adapter(MysteryGame())


def test_arena_tiled_single_tile_carry_parity():
    """Arena on the entity-TILED SyncTest kernel: the reduction-phase
    single-tile path (whole world in one VMEM tile, inline full-plane
    centroids) must bit-match the XLA scan carry-for-carry."""
    rng = np.random.default_rng(21)
    script = rng.integers(0, 64, size=(45, P, 1), dtype=np.uint8)
    xla = drive(Arena(P, 256), "xla", script, check_distance=4)
    tiled = drive(
        Arena(P, 256), "pallas-tiled-interpret", script, check_distance=4
    )
    assert_carry_equal(xla.carry, tiled.carry)
    xla.check()
    tiled.check()


def test_arena_sharded_kernel_support_matrix():
    """The SyncTest tiled core shards arena via reduce INJECTION (the
    per-frame reductions a resim needs are computable at tick launch —
    ring snapshots + live state — so complete psum'd sums are handed to
    the kernel); the request-path tick core must still refuse (P2P resim
    states are fresh under corrected inputs, so there is nothing to
    inject), and its auto resolves sharded arena to XLA."""
    from ggrs_tpu.parallel.mesh import make_mesh
    from ggrs_tpu.tpu.pallas_tiled import ShardedPallasTiledCore
    from ggrs_tpu.tpu.resim import ResimCore
    from ggrs_tpu.tpu.pallas_resim import ShardedPallasTickCore

    mesh = make_mesh(8)
    core = ShardedPallasTiledCore(Arena(P, 1024), P, 4, mesh, interpret=True)
    assert core.reduce_mode and core.inner.external_reduce
    rcore = ResimCore(Arena(P, 1024), max_prediction=6, num_players=P,
                      mesh=mesh)
    assert rcore.tick_backend == "xla"  # auto refuses the sharded combo
    with pytest.raises(AssertionError, match="tileable"):
        ShardedPallasTickCore(rcore, mesh)


def test_arena_sharded_tiled_carry_parity():
    """Sharded arena on the tiled kernel (reduce injection) must bit-match
    the sharded XLA scan AND the unsharded whole-batch kernel,
    carry-for-carry, over a forced-rollback run."""
    from ggrs_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    rng = np.random.default_rng(33)
    script = rng.integers(0, 64, size=(40, P, 1), dtype=np.uint8)

    def drive_mesh(backend):
        sess = TpuSyncTestSession(
            Arena(P, 1024),
            num_players=P,
            check_distance=4,
            flush_interval=10_000,
            backend=backend,
            mesh=mesh,
        )
        for i in range(4):
            sess.advance_frames(script[i * 10 : (i + 1) * 10])
        return sess

    tiled = drive_mesh("pallas-tiled-interpret")
    xla = drive_mesh("xla")
    assert_carry_equal(tiled.carry, xla.carry)
    tiled.check()
    plain = drive(Arena(P, 1024), "pallas-interpret", script, 4, batches=4)
    assert_carry_equal(tiled.carry, plain.carry)
    # the sharded carry is actually partitioned over the mesh
    shard = tiled.carry["state"]["pos"].addressable_shards[0]
    assert shard.data.shape[0] == 1024 // mesh.shape["entity"]
