"""SyncTestSession request shapes + determinism checks (parity with
tests/test_synctest_session.rs)."""

import pytest

from ggrs_tpu import (
    AdvanceFrame,
    InvalidRequest,
    LoadGameState,
    MismatchedChecksum,
    SaveGameState,
    SessionBuilder,
)
from stubs import GameStub, RandomChecksumGameStub


def make_session(check_distance=2, players=2, input_delay=0):
    return (
        SessionBuilder(input_size=1)
        .with_num_players(players)
        .with_check_distance(check_distance)
        .with_input_delay(input_delay)
        .start_synctest_session()
    )


def test_check_distance_too_big_rejected():
    with pytest.raises(InvalidRequest):
        SessionBuilder(input_size=1).with_check_distance(8).start_synctest_session()


def test_missing_input_rejected():
    sess = make_session()
    with pytest.raises(InvalidRequest):
        sess.advance_frame()


def test_request_shape_with_rollbacks():
    """After passing check_distance frames, every tick is: load, adv,
    (save, adv) x (dist-1), save, adv — 6 requests at distance 2
    (tests/test_synctest_session.rs:46-58)."""
    sess = make_session(check_distance=2)
    stub = GameStub()
    for frame in range(10):
        for h in range(2):
            sess.add_local_input(h, bytes([frame % 5]))
        requests = sess.advance_frame()
        if frame <= 2:
            assert len(requests) == 2  # save, advance
            assert isinstance(requests[0], SaveGameState)
            assert isinstance(requests[1], AdvanceFrame)
        else:
            kinds = [type(r) for r in requests]
            assert kinds == [
                LoadGameState,
                AdvanceFrame,
                SaveGameState,
                AdvanceFrame,
                SaveGameState,
                AdvanceFrame,
            ]
        stub.handle_requests(requests)


def test_deterministic_stub_passes_long_run():
    sess = make_session(check_distance=4)
    stub = GameStub()
    for frame in range(200):
        for h in range(2):
            sess.add_local_input(h, bytes([(frame * (h + 1)) % 7]))
        stub.handle_requests(sess.advance_frame())
    # resimulated 4 frames per tick after warmup
    assert stub.advanced > 200


def test_input_delay_works():
    sess = make_session(check_distance=2, input_delay=3)
    stub = GameStub()
    for frame in range(50):
        for h in range(2):
            sess.add_local_input(h, bytes([frame % 3]))
        stub.handle_requests(sess.advance_frame())


def test_random_checksums_detected():
    """Negative control: nondeterministic checksums must trip
    MismatchedChecksum (tests/test_synctest_session.rs:87-103)."""
    sess = make_session(check_distance=2)
    stub = RandomChecksumGameStub()
    with pytest.raises(MismatchedChecksum):
        for frame in range(50):
            for h in range(2):
                sess.add_local_input(h, bytes([0]))
            stub.handle_requests(sess.advance_frame())


def test_check_distance_zero_never_saves():
    sess = make_session(check_distance=0)
    stub = GameStub()
    for frame in range(20):
        for h in range(2):
            sess.add_local_input(h, bytes([1]))
        requests = sess.advance_frame()
        assert [type(r) for r in requests] == [AdvanceFrame]
        stub.handle_requests(requests)


def make_deferred_session(lag, check_distance=2):
    return (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_check_distance(check_distance)
        .with_deferred_checksum_verification(lag)
        .start_synctest_session()
    )


def test_deferred_verification_clean_run():
    """Deferred mode on a deterministic stub: no mismatch ever raised and
    the observation journal stays bounded."""
    sess = make_deferred_session(lag=5)
    stub = GameStub()
    for frame in range(60):
        for h in range(2):
            sess.add_local_input(h, bytes([frame % 7]))
        stub.handle_requests(sess.advance_frame())
    sess.flush_checksum_checks()
    assert not sess._pending_checks
    assert stub.advanced > 60  # rollbacks still happened every tick


def test_deferred_verification_detects_mismatch_within_lag():
    """A nondeterministic game must still trip MismatchedChecksum, at most
    `lag` ticks after the eager path would have."""
    lag = 4
    sess = make_deferred_session(lag=lag)
    stub = RandomChecksumGameStub()
    with pytest.raises(MismatchedChecksum):
        for frame in range(60):
            for h in range(2):
                sess.add_local_input(h, bytes([0]))
            stub.handle_requests(sess.advance_frame())
        sess.flush_checksum_checks()


def test_deferred_flush_detects_tail_mismatch():
    """Mismatches still pending at the end of a run surface on flush."""
    sess = make_deferred_session(lag=50)  # larger than the whole run
    stub = RandomChecksumGameStub()
    for frame in range(20):
        for h in range(2):
            sess.add_local_input(h, bytes([0]))
        stub.handle_requests(sess.advance_frame())
    with pytest.raises(MismatchedChecksum):
        sess.flush_checksum_checks()
