"""SyncLayer behavior (parity with reference in-module tests,
src/sync_layer.rs:280-344)."""

import pytest

from ggrs_tpu.errors import PredictionThreshold
from ggrs_tpu.frame_info import PlayerInput
from ggrs_tpu.sync_layer import ConnectionStatus, SyncLayer
from ggrs_tpu.types import SaveGameState


def test_reach_prediction_threshold():
    sl = SyncLayer(2, 8, 1)
    with pytest.raises(PredictionThreshold):
        for i in range(20):
            sl.add_local_input(0, PlayerInput(i, bytes([i])))
            sl.advance_frame()


def test_different_delays():
    sl = SyncLayer(2, 8, 1)
    p1_delay, p2_delay = 2, 0
    sl.set_frame_delay(0, p1_delay)
    sl.set_frame_delay(1, p2_delay)
    status = [ConnectionStatus(), ConnectionStatus()]

    for i in range(20):
        gi = PlayerInput(i, bytes([i]))
        # remote adds skip the prediction-threshold gate
        sl.add_remote_input(0, gi)
        sl.add_remote_input(1, gi)
        status[0].last_frame = i
        status[1].last_frame = i
        if i >= 3:
            sync_inputs = sl.synchronized_inputs(status)
            assert sync_inputs[0][0][0] == i - p1_delay
            assert sync_inputs[1][0][0] == i - p2_delay
        sl.advance_frame()


def test_snapshot_ring_save_load_roundtrip():
    sl = SyncLayer(1, 8, 1)
    req = sl.save_current_state()
    assert isinstance(req, SaveGameState) and req.frame == 0
    req.cell.save(0, {"x": 42}, 123)
    sl.advance_frame()
    load = sl.load_frame(0)
    assert load.frame == 0
    assert load.cell.load() == {"x": 42}
    assert load.cell.checksum == 123
    assert sl.current_frame == 0


def test_load_frame_outside_window_fails():
    sl = SyncLayer(1, 4, 1)
    sl.save_current_state().cell.save(0, 0, None)
    for _ in range(6):
        sl.advance_frame()
    with pytest.raises(AssertionError):
        sl.load_frame(0)  # 6 frames back > max_prediction 4
