"""Host-side fixture games for session tests.

Mirrors the reference's test strategy (tests/stubs.rs): a tiny deterministic
integer state machine that fulfills requests and hashes its state for
checksums, plus a negative control whose checksums are intentionally
nondeterministic (must trip SyncTest's MismatchedChecksum).
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import List

from ggrs_tpu import AdvanceFrame, InputStatus, LoadGameState, SaveGameState

INPUT_SIZE = 1


@dataclass
class StateStub:
    frame: int = 0
    state: int = 0

    def advance(self, inputs) -> None:
        self.frame += 1
        for buf, status in inputs:
            if status != InputStatus.DISCONNECTED:
                self.state += buf[0] + 1
            else:
                self.state += 13


def _hash_stub(s: StateStub) -> int:
    # deterministic integer hash of (frame, state)
    h = (s.frame * 2654435761 + s.state * 40503 + 7) % (1 << 64)
    return h


class GameStub:
    """Fulfills the ordered request list against a StateStub."""

    def __init__(self):
        self.gs = StateStub()
        self.saved_frames: List[int] = []
        self.loaded_frames: List[int] = []
        self.advanced = 0
        # frame -> state after advancing INTO that frame; rollback
        # resimulations overwrite entries with corrected values
        self.history = {}

    def checksum(self, s: StateStub) -> int:
        return _hash_stub(s)

    def handle_requests(self, requests) -> None:
        for req in requests:
            if isinstance(req, SaveGameState):
                assert req.frame == self.gs.frame
                self.saved_frames.append(req.frame)
                req.cell.save(
                    req.frame, StateStub(self.gs.frame, self.gs.state), self.checksum(self.gs)
                )
            elif isinstance(req, LoadGameState):
                data = req.cell.load()
                assert data is not None
                self.loaded_frames.append(data.frame)
                self.gs = StateStub(data.frame, data.state)
            elif isinstance(req, AdvanceFrame):
                self.gs.advance(req.inputs)
                self.advanced += 1
                self.history[self.gs.frame] = self.gs.state
            else:
                raise TypeError(req)


class RandomChecksumGameStub(GameStub):
    """Saves a random checksum each time: SyncTest must flag it
    (tests/stubs.rs:67-106)."""

    def __init__(self):
        super().__init__()
        self._rng = random.Random(1234)

    def checksum(self, s: StateStub) -> int:
        return self._rng.getrandbits(64)


class EnumInput:
    """Fieldless-enum input contract (tests/stubs_enum.rs:18-29): the valid
    encodings are sparse, non-contiguous byte patterns, and decoding
    anything else is an error — the CheckedBitPattern analog for the
    byte-string input POD."""

    UP, DOWN, LEFT, RIGHT = 0x00, 0x01, 0x40, 0xFA  # deliberately sparse
    VALUES = (UP, DOWN, LEFT, RIGHT)

    @staticmethod
    def encode(value: int) -> bytes:
        assert value in EnumInput.VALUES
        return bytes([value])

    @staticmethod
    def decode(buf: bytes) -> int:
        value = buf[0]
        if value not in EnumInput.VALUES:
            raise ValueError(f"invalid EnumInput bit pattern 0x{value:02x}")
        return value


class GameStubEnum(GameStub):
    """GameStub over enum inputs (tests/stubs_enum.rs): every confirmed or
    predicted input must decode to a valid enum member after crossing the
    queue/compression/wire machinery byte-exactly. Blank predictions decode
    to UP (0x00), like the reference's zeroed default. Decoding raises on
    any corrupted pattern; the state march itself is GameStub's."""

    def handle_requests(self, requests) -> None:
        for req in requests:
            if isinstance(req, AdvanceFrame):
                for buf, status in req.inputs:
                    if status != InputStatus.DISCONNECTED:
                        EnumInput.decode(buf)
        super().handle_requests(requests)
