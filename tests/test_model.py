"""Flagship model determinism: the jitted device step must agree bit-for-bit
with the numpy oracle, and checksums must be order-invariant and stable."""

import numpy as np

from ggrs_tpu.models import ex_game
from ggrs_tpu.ops import fixed_point as fx


def random_inputs(rng, frames, players):
    return rng.integers(0, 16, size=(frames, players), dtype=np.uint8)


def test_oracle_step_moves_entities():
    state = ex_game.init_oracle(num_players=2, num_entities=64)
    s0 = state["pos"].copy()
    inputs = np.array([ex_game.INPUT_UP, ex_game.INPUT_UP], dtype=np.uint8)
    statuses = np.zeros(2, dtype=np.int32)
    for _ in range(30):
        state = ex_game.step_oracle(state, inputs, statuses, 2)
    assert state["frame"] == 30
    assert np.any(state["pos"] != s0)
    # velocity magnitude stays clamped
    v = state["vel"].astype(np.int64)
    assert np.all(v[:, 0] ** 2 + v[:, 1] ** 2 <= ex_game.MAX_SPEED**2)


def test_device_matches_oracle_bitexact():
    import jax

    game = ex_game.ExGame(num_players=2, num_entities=256)
    dev_state = game.init_state()
    ora_state = ex_game.init_oracle(num_players=2, num_entities=256)

    step = jax.jit(game.step)
    rng = np.random.default_rng(7)
    inputs = random_inputs(rng, 40, 2)
    statuses = np.zeros(2, dtype=np.int32)
    for f in range(40):
        dev_state = step(dev_state, inputs[f].reshape(2, 1), statuses)
        ora_state = ex_game.step_oracle(ora_state, inputs[f], statuses, 2)

    fetched = jax.device_get(dev_state)
    for key in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(np.asarray(fetched[key]), ora_state[key])

    hi, lo = jax.jit(game.checksum)(dev_state)
    ohi, olo = ex_game.checksum_oracle(ora_state)
    assert int(hi) == ohi and int(lo) == olo


def test_step_is_deterministic_across_replays():
    """Same snapshot + same inputs => bit-identical result, repeatedly — the
    property the whole rollback correctness model rests on."""
    import jax

    game = ex_game.ExGame(num_players=2, num_entities=128)
    state = game.init_state()
    step = jax.jit(game.step)
    inputs = np.array([[3], [9]], dtype=np.uint8)
    statuses = np.zeros(2, dtype=np.int32)

    out1 = step(state, inputs, statuses)
    out2 = step(state, inputs, statuses)
    c1 = jax.jit(game.checksum)(out1)
    c2 = jax.jit(game.checksum)(out2)
    assert int(c1[0]) == int(c2[0]) and int(c1[1]) == int(c2[1])


def test_disconnected_players_spin():
    state = ex_game.init_oracle(num_players=2, num_entities=4)
    inputs = np.zeros(2, dtype=np.uint8)
    statuses = np.array([0, 2], dtype=np.int32)  # player 1 disconnected
    rot0 = state["rot"].copy()
    state = ex_game.step_oracle(state, inputs, statuses, 2)
    # entities of player 0 (even indices) unchanged; player 1's spin
    assert np.all(state["rot"][0::2] == rot0[0::2])
    assert np.all(state["rot"][1::2] != rot0[1::2])


def test_checksum_sensitivity():
    s1 = ex_game.init_oracle(num_players=2, num_entities=64)
    s2 = ex_game.init_oracle(num_players=2, num_entities=64)
    s2["pos"] = s2["pos"].copy()
    s2["pos"][3, 0] += 1
    assert ex_game.checksum_oracle(s1) != ex_game.checksum_oracle(s2)


def test_isqrt_exact():
    vals = np.arange(0, 1 << 16, 37, dtype=np.int32)
    vals = np.concatenate([vals, np.array([0, 1, 2, 3, (1 << 23) - 1], dtype=np.int32)])
    got = fx.isqrt24(vals, np)
    want = np.floor(np.sqrt(vals.astype(np.float64))).astype(np.int32)
    np.testing.assert_array_equal(got, want)


def test_substeps_oracle_parity_and_pallas():
    """ExGame(substeps=k): k physics sub-iterations per frame, frame +1.
    Device, oracle and pallas adapter must agree bit-for-bit."""
    import jax

    from ggrs_tpu.models.ex_game import ExGame, init_oracle, step_oracle
    from ggrs_tpu.tpu import TpuSyncTestSession

    game = ExGame(2, 256, substeps=3)
    script = np.stack(
        [np.arange(12, dtype=np.uint8) % 16, (np.arange(12, dtype=np.uint8) * 5) % 16],
        axis=1,
    )[:, :, None]
    sess = TpuSyncTestSession(
        game, num_players=2, check_distance=2, flush_interval=100,
        backend="pallas-interpret",
    )
    sess.advance_frames(script)
    sess.check()

    state = init_oracle(2, 256)
    statuses = np.zeros((2,), dtype=np.int32)
    for f in range(12):
        state = step_oracle(state, script[f], statuses, 2, substeps=3)
    dev = jax.device_get(sess.carry["state"])
    assert int(dev["frame"]) == 12
    for k in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(np.asarray(dev[k]), state[k])
