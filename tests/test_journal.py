"""Durable input journal + point-in-time recovery.

The durability contract under test: with journaling enabled, losing the
ENTIRE host — process, RAM, checkpoint ticket — loses zero confirmed
frames, because the journal's crash-consistent confirmed-row log plus
the determinism contract (simulation = pure function of (initial state,
confirmed inputs)) rebuild the match bit-exactly by resimulation. The
storm half: SIGKILL at any instant (mid-append, mid-rotation) never
yields a partial record on reopen, injected segment corruption surfaces
as typed JournalCorrupt with recovery falling to the next ladder tier,
and a disk refusing appends degrades the lane to unjournaled — never a
wedged host.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from ggrs_tpu.errors import InvalidRequest, JournalCorrupt, JournalStalled
from ggrs_tpu.journal import (
    JournalWriter,
    batch_resim_journals,
    corrupt_segment,
    journal_coverage,
    journal_files,
    read_journal_script,
    scan_journal,
    scripts_from_journal,
    seed_journal,
)

PLAYERS = 2
ENTITIES = 8


def _rows(frames, players=PLAYERS, input_size=1, seed=0):
    rng = np.random.default_rng(seed)
    inputs = rng.integers(0, 16, size=(frames, players, input_size),
                          dtype=np.uint8)
    statuses = np.zeros((frames, players), np.int32)
    return inputs, statuses


# ----------------------------------------------------------------------
# record framing, rotation, resume
# ----------------------------------------------------------------------


def test_wal_roundtrip_across_rotations(tmp_path):
    path = str(tmp_path / "j")
    inputs, statuses = _rows(83)
    w = JournalWriter(path, meta={"match_id": 7}, segment_bytes=300)
    rng = np.random.default_rng(1)
    off = 0
    while off < 83:
        n = min(int(rng.integers(1, 7)), 83 - off)
        assert w.append_rows(off, inputs[off:off + n],
                             statuses[off:off + n]) == n
        off += n
    w.close()
    assert w.rotations > 2  # the 300-byte budget forced real rotations
    got_i, got_s, meta = read_journal_script(path)
    np.testing.assert_array_equal(got_i, inputs)
    np.testing.assert_array_equal(got_s, statuses)
    assert meta["match_id"] == 7

    # resume: the writer picks up at the durable frontier, verifies the
    # redriven overlap bit-for-bit, appends fresh rows past it
    w2 = JournalWriter(path, segment_bytes=300)
    assert w2.next_frame == 83
    more_i, more_s = _rows(6, seed=9)
    w2.append_rows(80, np.concatenate([inputs[80:], more_i[:3]]),
                   np.concatenate([statuses[80:], more_s[:3]]))
    assert w2.verified_rows == 3 and w2.next_frame == 86
    w2.close()
    got_i, _, _ = read_journal_script(path)
    assert got_i.shape[0] == 86

    # a diverging overlap is typed corruption, not silent adoption
    w3 = JournalWriter(path, segment_bytes=300)
    bad = inputs[70:72].copy()
    bad[0, 0, 0] ^= 1
    with pytest.raises(JournalCorrupt):
        w3.append_rows(70, bad, statuses[70:72])
    w3.close()

    # a gap above the frontier can never silently enter the journal
    w4 = JournalWriter(path, segment_bytes=300)
    with pytest.raises(InvalidRequest):
        w4.append_rows(90, more_i, more_s)
    w4.close()


def test_torn_tail_truncated_never_a_partial_record(tmp_path):
    path = str(tmp_path / "j")
    inputs, statuses = _rows(20)
    w = JournalWriter(path)
    w.append_rows(0, inputs, statuses)
    w.close()
    seg = sorted(
        n for n in os.listdir(path) if n.endswith(".wal")
    )[-1]
    # crash residue: a torn half-record at the tail
    with open(os.path.join(path, seg), "ab") as f:
        f.write(b"\xa7\x02\x10\x00\x00\x00partial")
    scan = scan_journal(path, repair=True)
    assert scan.next_frame == 20 and scan.torn_bytes > 0
    got_i, _ = scan.script()
    np.testing.assert_array_equal(got_i, inputs)
    # the repair truncated in place: a fresh writer appends cleanly
    w2 = JournalWriter(path)
    assert w2.next_frame == 20
    w2.append_rows(20, *_rows(3, seed=5))
    w2.close()
    assert read_journal_script(path)[0].shape[0] == 23


def test_corrupt_segment_quarantined_typed(tmp_path):
    path = str(tmp_path / "j")
    inputs, statuses = _rows(60)
    w = JournalWriter(path, segment_bytes=250)
    for f in range(60):
        w.append_rows(f, inputs[f:f + 1], statuses[f:f + 1])
    w.close()
    names = sorted(n for n in os.listdir(path) if n.endswith(".wal"))
    assert len(names) >= 3
    corrupt_segment(path, segment=1)
    scan = scan_journal(path, repair=True)
    # typed verdict, quarantined file, usable contiguous prefix (which
    # keeps the corrupt segment's CRC-valid LEADING records)
    assert scan.corrupt and isinstance(scan.corrupt[0], JournalCorrupt)
    assert scan.gap
    assert any(n.endswith(".corrupt") for n in os.listdir(path))
    got_i, _ = scan.script()
    assert 0 < got_i.shape[0] < 60
    np.testing.assert_array_equal(got_i, inputs[: got_i.shape[0]])
    # a writer refuses to append over the gap — typed, not a crash
    with pytest.raises(JournalCorrupt):
        JournalWriter(path)


def test_final_segment_mid_corruption_quarantines_not_truncates(tmp_path):
    """An SDC flip in the MIDDLE of the active segment (valid records
    still follow it) is corruption, not crash tearing: the scan must
    quarantine typed instead of silently truncating acknowledged
    durable rows — only a flip with nothing valid after it is
    indistinguishable from a tear."""
    path = str(tmp_path / "j")
    inputs, statuses = _rows(30)
    w = JournalWriter(path)  # one big segment: everything is "final"
    for f in range(30):
        w.append_rows(f, inputs[f:f + 1], statuses[f:f + 1])
    w.close()
    corrupt_segment(path, segment=0)  # mid-file: records follow
    scan = scan_journal(path, repair=True)
    assert scan.corrupt and isinstance(scan.corrupt[0], JournalCorrupt)
    assert any(n.endswith(".corrupt") for n in os.listdir(path))
    # the valid leading rows are still recovered by THIS scan
    got_i, _ = scan.script()
    assert 0 < got_i.shape[0] < 30
    np.testing.assert_array_equal(got_i, inputs[: got_i.shape[0]])


def test_resume_refuses_identity_mismatch(tmp_path):
    """The self-describing META is checked at resume: a key collision
    onto another match's journal refuses typed instead of splicing two
    lineages (or spuriously failing verify later)."""
    path = str(tmp_path / "j")
    w = JournalWriter(path, meta={"match_id": 7, "num_players": PLAYERS})
    w.append_rows(0, *_rows(5))
    w.close()
    with pytest.raises(JournalCorrupt):
        JournalWriter(path, meta={"match_id": 8})
    # same identity resumes fine
    w2 = JournalWriter(path, meta={"match_id": 7})
    assert w2.next_frame == 5
    w2.close()


def test_seize_and_seed_roundtrip(tmp_path):
    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    inputs, statuses = _rows(31)
    w = JournalWriter(src, meta={"match_id": 3}, segment_bytes=200)
    w.append_rows(0, inputs, statuses)
    w.close()
    files = journal_files(src)
    assert files
    seed_journal(dst, files)
    got_i, got_s, meta = read_journal_script(dst)
    np.testing.assert_array_equal(got_i, inputs)
    assert meta["match_id"] == 3
    with pytest.raises(InvalidRequest):
        seed_journal(dst, {"../escape": b"x"})
    # a re-seed CLEARS stale residue first: a previous hosting's
    # higher-index segment must not splice into the seized history
    with open(os.path.join(dst, "seg-000000ff.wal"), "wb") as f:
        f.write(b"stale lineage")
    seed_journal(dst, files)
    assert not os.path.exists(os.path.join(dst, "seg-000000ff.wal"))
    got_i2, _, _ = read_journal_script(dst)
    np.testing.assert_array_equal(got_i2, inputs)


# ----------------------------------------------------------------------
# satellite: InputRecorder drain API — bounded memory, correct tail
# ----------------------------------------------------------------------


def test_recorder_drain_frees_rows_keeps_tail_correct():
    from ggrs_tpu.types import AdvanceFrame, InputStatus
    from ggrs_tpu.utils.replay import InputRecorder

    def adv(v):
        return AdvanceFrame(
            inputs=[(bytes([v]), InputStatus.CONFIRMED)] * PLAYERS
        )

    full = InputRecorder()
    draining = InputRecorder()
    drained_rows = []
    for f in range(40):
        full.observe([adv(f)])
        draining.observe([adv(f)])
        if f and f % 7 == 0:
            full.confirm_through(f - 3)
            draining.confirm_through(f - 3)
            out = draining.drain_confirmed()
            if out is not None:
                start, inputs, statuses = out
                assert start == len(drained_rows)
                drained_rows.extend(inputs[:, 0, 0].tolist())
    # memory actually freed: only the undrained tail remains
    assert len(draining._rows) < len(full._rows)
    assert draining.drained_through == len(drained_rows) > 0
    # confirm a little further WITHOUT draining: the undrained tail
    full.confirm_through(37)
    draining.confirm_through(37)
    # absolute frontier identical on both recorders...
    assert draining.confirmed_frames == full.confirmed_frames
    # ...and the undrained tail script matches the full recorder's slice
    f_i, f_s = full.confirmed_script()
    t_i, t_s = draining.confirmed_script()
    np.testing.assert_array_equal(t_i, f_i[draining.drained_through:])
    np.testing.assert_array_equal(t_s, f_s[draining.drained_through:])
    # drained + tail reassemble the full confirmed prefix exactly
    assert drained_rows == f_i[: len(drained_rows), 0, 0].tolist()


def test_mid_match_adoption_rebases_fresh_journal(tmp_path):
    """A mid-match adopted lane (migration without carried bytes) never
    observes the frames its previous host played: the recorder
    re-anchors its drain at the first observed final row and an EMPTY
    journal re-bases onto that first append — recording first_frame > 0
    (tail coverage; the genesis-resim tier refuses it by design)
    instead of waiting forever while rows pile up."""
    from ggrs_tpu.types import AdvanceFrame, InputStatus
    from ggrs_tpu.utils.replay import InputRecorder

    rec = InputRecorder()
    rec._next_frame = 50  # the adopt point: frames 0..49 played elsewhere
    for f in range(50, 70):
        rec.observe([AdvanceFrame(
            inputs=[(bytes([f % 200]), InputStatus.CONFIRMED)] * PLAYERS
        )])
    rec.confirm_through(64)
    out = rec.drain_confirmed()
    assert out is not None
    start, inputs, statuses = out
    assert start == 50 and inputs.shape[0] == 15
    path = str(tmp_path / "rebase")
    w = JournalWriter(path, meta={"match_id": 1})
    w.append_rows(start, inputs, statuses)
    assert w.base_frame == 50 and w.next_frame == 65
    w.close()
    got_i, _, meta = read_journal_script(path)
    assert meta["first_frame"] == 50
    assert got_i.shape[0] == 15
    # a resumed writer agrees with the rebased base
    w2 = JournalWriter(path)
    assert w2.base_frame == 50 and w2.next_frame == 65
    w2.close()


# ----------------------------------------------------------------------
# satellite: kill-mid-write regression — the real-SIGKILL hammer
# (test_checkpoint.py's pattern pointed at appends AND rotation)
# ----------------------------------------------------------------------


def test_journal_survives_real_sigkill_mid_append_and_rotation(tmp_path):
    """A child appends rows in a tight loop with a tiny segment budget
    (so the kill races appends AND rotations); SIGKILLed at an
    arbitrary instant, the reopened journal must yield a contiguous,
    bit-correct prefix of what the child acknowledged — never a
    partial or corrupted record."""
    path = str(tmp_path / "hammer")
    code = (
        "import sys, numpy as np\n"
        "sys.path.insert(0, %r)\n"
        "from ggrs_tpu.journal import JournalWriter\n"
        "w = JournalWriter(%r, meta={'m': 1}, segment_bytes=256,\n"
        "                  fsync_every=0)\n"
        "f = w.next_frame\n"
        "while True:\n"
        "    n = 1 + f %% 3\n"
        "    inp = np.full((n, 2, 1), f %% 251, np.uint8)\n"
        "    for k in range(n):\n"
        "        inp[k] = (f + k) %% 251\n"
        "    st = np.zeros((n, 2), np.int32)\n"
        "    w.append_rows(f, inp, st)\n"
        "    f += n\n" % (os.getcwd(), path)
    )
    for round_ in range(2):
        child = subprocess.Popen([sys.executable, "-c", code],
                                 cwd=os.getcwd())
        try:
            deadline = time.monotonic() + 15
            while not os.path.isdir(path) or not os.listdir(path):
                assert child.poll() is None, "writer died before writing"
                assert time.monotonic() < deadline, "writer never wrote"
                time.sleep(0.01)
            time.sleep(0.3 + 0.2 * round_)  # let it hammer rotations
        finally:
            child.send_signal(signal.SIGKILL)
            child.wait(timeout=10)
        scan = scan_journal(path, repair=True)
        # no corrupt segments — SIGKILL can only tear the TAIL
        assert scan.corrupt == [] and not scan.gap
        assert scan.next_frame > 0
        inputs, statuses = scan.script()
        # every recovered row holds exactly the value the child wrote
        np.testing.assert_array_equal(
            inputs[:, 0, 0],
            (np.arange(scan.next_frame) % 251).astype(np.uint8),
        )
        # round 2 RESUMES over the truncated tail and keeps hammering:
        # kill-mid-rotation must leave a resumable journal


# ----------------------------------------------------------------------
# the host tap: parity, ENOSPC degrade
# ----------------------------------------------------------------------


def _twin_with_journal(tmp_path, specs, *, mesh=None, name="jr"):
    from ggrs_tpu.fleet.island import make_game, run_twin
    from ggrs_tpu.serve.host import SessionHost
    from ggrs_tpu.utils.clock import FakeClock

    game = make_game(players=PLAYERS, entities=ENTITIES)
    host = SessionHost(
        game,
        max_prediction=8,
        num_players=PLAYERS,
        max_sessions=sum(s.players for s in specs),
        clock=FakeClock(),
        idle_timeout_ms=0,
        mesh=mesh,
        journal_dir=str(tmp_path / name),
    )
    islands = run_twin(specs, host=host, game=game)
    return game, host, islands


def _specs(n, *, ticks=60, wan_first=True):
    from ggrs_tpu.fleet.island import MatchSpec

    return [
        MatchSpec(match_id=m, players=PLAYERS, ticks=ticks, seed=300 + m,
                  entities=ENTITIES,
                  wan={} if (wan_first and m == 0) else None)
        for m in range(n)
    ]


def test_host_journal_peers_identical_and_resim_parity(tmp_path):
    """The acceptance triangle on a hosted fleet: every peer of a match
    journals bit-identical confirmed rows; the journal-derived submit
    scripts equal what the players actually fed in; and a batched
    megabatch resimulation from the journal ALONE reproduces the live
    desync detector's checksum history bit-for-bit."""
    specs = _specs(2)
    game, host, islands = _twin_with_journal(tmp_path, specs)
    jdir = str(tmp_path / "jr")
    paths = sorted(os.path.join(jdir, n) for n in os.listdir(jdir))
    assert len(paths) == 4  # every p2p lane journaled
    scripts = [read_journal_script(p)[:2] for p in paths]
    # lanes 0/1 = match 0's peers, 2/3 = match 1's (attach order)
    for a, b in ((0, 1), (2, 3)):
        n = min(scripts[a][0].shape[0], scripts[b][0].shape[0])
        assert n > 40
        np.testing.assert_array_equal(scripts[a][0][:n], scripts[b][0][:n])
        np.testing.assert_array_equal(scripts[a][1][:n], scripts[b][1][:n])
    # the delay-shifted submit scripts are exactly the played scripts
    for m, idx in ((0, 0), (1, 2)):
        isl = islands[m]
        derived = scripts_from_journal(
            scripts[idx][0], input_delay=isl.spec.input_delay,
            ticks=isl.spec.ticks,
        )
        cov = journal_coverage(
            scripts[idx][0], input_delay=isl.spec.input_delay
        )
        assert cov > 40
        for k, script in derived.items():
            assert script == isl.scripts[k][: len(script)]
    # journal-only world rebuild: checksum-history parity vs the live run
    res = batch_resim_journals(game, [scripts[0], scripts[2]])
    compared = 0
    for mi, m in enumerate((0, 1)):
        for peer, hist in islands[m].histories().items():
            for f, c in hist.items():
                if f < res[mi]["frames"]:
                    assert res[mi]["checksums"][f] == c, (m, peer, f)
                    compared += 1
    assert compared >= 8
    sec = host._host_section()["journal"]
    assert sec["lanes"] == 4 and sec["frames_journaled"] > 160
    assert sec["degraded"] == 0


def test_sharded_host_journal_matches_single_device(tmp_path):
    """The tap sits above the device layout: a session-mesh host fed
    identical traffic journals byte-identical files to the
    single-device twin's."""
    from ggrs_tpu.parallel.mesh import make_session_mesh

    specs = _specs(1, ticks=48, wan_first=False)
    _twin_with_journal(tmp_path, specs, name="single")
    _twin_with_journal(
        tmp_path, specs, mesh=make_session_mesh(8), name="sharded"
    )
    single = journal_files(str(tmp_path / "single" / "lane0"))
    sharded = journal_files(str(tmp_path / "sharded" / "lane0"))
    assert single and sorted(single) == sorted(sharded)
    for name in single:
        assert single[name] == sharded[name], name


def test_enospc_degrades_lane_to_unjournaled_never_wedges(tmp_path):
    """The storage tier's ENOSPC arm via the deterministic fault seam:
    an injected filesystem refusal mid-serve degrades the lane's tap
    (typed JournalStalled accounted + invariant trip) while the match
    keeps advancing to completion with zero desyncs."""
    from ggrs_tpu.fleet.island import (
        FRAME_MS, MatchIsland, make_game, step_islands,
    )
    from ggrs_tpu.obs import GLOBAL_TELEMETRY
    from ggrs_tpu.serve.faults import FaultInjector, FaultPlan
    from ggrs_tpu.serve.host import SessionHost
    from ggrs_tpu.utils.clock import FakeClock

    GLOBAL_TELEMETRY.enabled = True
    GLOBAL_TELEMETRY.dump_dir = str(tmp_path)  # forensics stay out of cwd
    try:
        spec = _specs(1, ticks=60, wan_first=False)[0]
        game = make_game(players=PLAYERS, entities=ENTITIES)
        host = SessionHost(
            game, max_prediction=8, num_players=PLAYERS, max_sessions=2,
            clock=FakeClock(), idle_timeout_ms=0,
            journal_dir=str(tmp_path / "jr"),
        )
        island = MatchIsland.build(spec)
        island.attach(host)
        plan = FaultPlan(3, 40, kinds=("journal_stall",),
                         events_per_kind=1, start=16)
        inj = FaultInjector(host, plan).install()
        for tick in range(1, 900):
            inj.advance(tick)
            step_islands(host, [island])
            host.clock.advance(FRAME_MS)
            if island.done:
                break
        assert island.done and island.desyncs == 0
        assert inj.fired["journal_stall"] >= 1
        assert host.journal_lanes_degraded >= 1
        assert any(
            t.invariant == "journal_degraded"
            for t in host.invariant_trips
        )
        # the victim lane serves on, unjournaled; at most the other
        # lane still journals
        taps = [
            lane.journal for lane in host._lanes.values()
            if lane.journal is not None
        ]
        assert len(taps) < 2
        snap = GLOBAL_TELEMETRY.snapshot()
        prom = GLOBAL_TELEMETRY.prometheus()
        for name in ("ggrs_journal_stalls_total", "ggrs_journal_rows_total"):
            assert name in snap["metrics"] and name in prom
        assert snap["metrics"]["ggrs_journal_stalls_total"]["values"][""] >= 1
    finally:
        GLOBAL_TELEMETRY.enabled = False
        GLOBAL_TELEMETRY.dump_dir = None
        GLOBAL_TELEMETRY.reset()


# ----------------------------------------------------------------------
# satellite: journal-backed recovery parity through the fleet ladder
# ----------------------------------------------------------------------


def _rig(tmp_path, **kw):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_fleet_control import Rig

    return Rig(tmp_path, **kw)


def _kill_totally(rig, victim):
    """In-process total host loss: freeze the victim's control conn,
    DESTROY its checkpoint ticket, and stop stepping it (the process-
    death analog the real-SIGKILL soak runs in test_fleet_process)."""
    vcore = rig.agents[victim]
    vcore.partition(120_000)
    rig.director.hosts[victim].peer.conn.partitioned = True
    cp = rig.director.hosts[victim].checkpoint
    if cp and cp.get("path") and os.path.exists(cp["path"]):
        os.remove(cp["path"])
    rig.director.hosts[victim].checkpoint = None
    rig.agents = [a for a in rig.agents if a is not vcore]
    return vcore


def test_journal_only_failover_bitwise_parity(tmp_path):
    """SIGKILL-equivalent + ticket destruction: recovery has NOTHING
    but the seized journal, rebuilds the match from genesis through the
    batched megabatch redrive, and the finished match is bitwise equal
    (checksum histories + canonical state digests) to the unfaulted
    twin — zero confirmed frames lost."""
    from ggrs_tpu.fleet.chaos import compare_with_twin
    from test_fleet_control import _spec

    rig = _rig(tmp_path, checkpoint_every=6)
    specs = [_spec(0, seed=500, ticks=160), _spec(1, seed=501, ticks=160)]
    owners = {s.match_id: rig.director.place_match(s) for s in specs}
    for _ in range(60):
        rig.pump(1)
    victim = owners[0]
    _kill_totally(rig, victim)
    for _ in range(300):
        rig.pump(1)
        if rig.director.hosts[victim].state == "dead":
            break
    fo = rig.director.failovers[-1]
    victims_matches = [m for m, h in owners.items() if h == victim]
    assert fo["tiers"] == {str(m): "journal" for m in victims_matches}
    assert fo["lost"] == []
    assert fo.get("journal_replayed_frames", 0) > 20
    rig.drive_done(cores=rig.agents)
    reports = rig.director.collect_reports()
    parity = compare_with_twin(specs, reports, set(victims_matches))
    assert parity["clean_exact"] and parity["faulted_exact"], parity
    # the dead host's matches are PLACED again, on a survivor
    for m in victims_matches:
        rec = rig.director.matches[m]
        assert rec["state"] == "placed" and rec["host"] != victim


def test_ticket_plus_journal_tier_verifies_tail(tmp_path):
    """Tier 2: the ticket survives, so failover imports it WITH the
    seized journal folded in — the survivor's resumed redrive is then
    verified row-for-row against the journaled tail (verified_rows on
    the resumed writer), and parity still holds."""
    from ggrs_tpu.fleet.chaos import compare_with_twin
    from test_fleet_control import _spec

    rig = _rig(tmp_path, checkpoint_every=6)
    specs = [_spec(0, seed=700, ticks=160), _spec(1, seed=701, ticks=160)]
    owners = {s.match_id: rig.director.place_match(s) for s in specs}
    for _ in range(60):
        rig.pump(1)
    victim = owners[0]
    vcore = rig.agents[victim]
    vcore.partition(120_000)
    rig.director.hosts[victim].peer.conn.partitioned = True
    rig.agents = [a for a in rig.agents if a is not vcore]
    for _ in range(300):
        rig.pump(1)
        if rig.director.hosts[victim].state == "dead":
            break
    fo = rig.director.failovers[-1]
    victims_matches = [m for m, h in owners.items() if h == victim]
    assert fo["tiers"] == {
        str(m): "ticket+journal" for m in victims_matches
    }
    # restore landed at the exact checkpoint frame (the ticket tier's
    # original guarantee, unchanged by the journal fold-in)
    for mid, frames in fo["restored"].items():
        assert fo["checkpoint_frames"][mid] == frames
    surv = rig.agents[0]
    rig.drive_done(cores=[surv])
    # the survivor's resumed writer verified the redriven tail
    verified = sum(
        lane.journal.writer.verified_rows
        for lane in surv.host._lanes.values()
        if lane.journal is not None
    )
    assert verified > 0
    reports = rig.director.collect_reports()
    parity = compare_with_twin(specs, reports, set(victims_matches))
    assert parity["clean_exact"] and parity["faulted_exact"], parity


def test_journal_rebuild_spills_to_a_survivor_with_room(tmp_path):
    """Match-granular fall-through on the journal tier: when the
    least-loaded survivor is FULL, the rebuild lands on the next one
    instead of marking the match lost."""
    from test_fleet_control import _spec

    rig = _rig(tmp_path, n_agents=3, max_sessions=4, checkpoint_every=6)
    # hosts 0/1/2 get one match each, then m3 lands on host 0 (lowest id
    # among least-loaded) — the victim owns TWO matches while each
    # survivor has room for exactly ONE more
    specs = [_spec(m, seed=21 + m, ticks=160) for m in range(4)]
    owners = {s.match_id: rig.director.place_match(s) for s in specs}
    assert owners == {0: 0, 1: 1, 2: 2, 3: 0}
    for _ in range(40):
        rig.pump(1)
    _kill_totally(rig, 0)
    for _ in range(400):
        rig.pump(1)
        if rig.director.hosts[0].state == "dead":
            break
    fo = rig.director.failovers[-1]
    # one rebuild per survivor: the first call rebuilds what fits and
    # reports the rest failed (HostFull, per-match isolation); the
    # remaining match falls through to the other survivor
    assert fo["tiers"] == {"0": "journal", "3": "journal"}
    assert fo["lost"] == []
    assert sorted(fo["restored_on_journal"]) == [1, 2]
    placed_on = {
        m: rig.director.matches[m]["host"] for m in (0, 3)
    }
    assert sorted(placed_on.values()) == [1, 2]


@pytest.mark.slow  # the fast single-kill arms above pin each tier;
# this composes migration (journal rides the ticket) + total loss
def test_migrated_journal_recovers_on_third_host(tmp_path):
    """The journal bytes ride migration tickets: migrate a match, then
    totally lose the DESTINATION — the journal seized there still
    covers genesis, so tier-3 recovery on a third host stays bitwise
    exact."""
    from ggrs_tpu.fleet.chaos import compare_with_twin
    from test_fleet_control import _spec

    rig = _rig(tmp_path, n_agents=3, checkpoint_every=6)
    spec = _spec(0, seed=900, ticks=160)
    src = rig.director.place_match(spec)
    for _ in range(40):
        rig.pump(1)
    dst = (src + 1) % 3
    rig.director.migrate_match(0, dst)
    # destination journals from GENESIS: the bytes moved with the ticket
    dcore = next(
        a for a in rig.agents if a.host_id == dst
    )
    key = dcore._island_journal[0]
    w = dcore.host._lanes[key].journal.writer
    assert w.base_frame == 0 and w.next_frame > 10
    for _ in range(30):
        rig.pump(1)
    _kill_totally(rig, dst)
    for _ in range(300):
        rig.pump(1)
        if rig.director.hosts[dst].state == "dead":
            break
    fo = rig.director.failovers[-1]
    assert fo["tiers"] == {"0": "journal"}, fo["tiers"]
    rig.drive_done(cores=rig.agents)
    parity = compare_with_twin(
        [spec], rig.director.collect_reports(), {0}
    )
    assert parity["clean_exact"] and parity["faulted_exact"], parity


@pytest.mark.parametrize("segment", [1, 0])
def test_corrupt_seized_journal_typed_fallback(tmp_path, segment):
    """Storm composition: ticket destroyed AND a seized-journal segment
    corrupted. A MIDDLE segment quarantines typed and recovery still
    rebuilds from the surviving genesis prefix (shorter, but bitwise on
    the unfaulted-twin contract); the FIRST segment takes genesis with
    it, so the match is recorded LOST — typed, never a crashed director
    or agent."""
    from ggrs_tpu.fleet.chaos import compare_with_twin
    from test_fleet_control import _spec

    rig = _rig(tmp_path, checkpoint_every=6)
    for core in rig.agents:
        core.journal_segment_bytes = 300  # several segments per match
    spec = _spec(0, seed=333, ticks=160)
    victim = rig.director.place_match(spec)
    for _ in range(80):
        rig.pump(1)
    vcore = [a for a in rig.agents if a.host_id == victim][0]
    jpath = vcore._journal_path(0)
    _kill_totally(rig, victim)
    names = sorted(n for n in os.listdir(jpath) if n.endswith(".wal"))
    assert len(names) >= 3  # the corruption target is NON-final
    # segment 0 is hit INSIDE its META record: no valid leading rows
    # survive, so genesis coverage is truly gone (a flip past the META
    # leaves a salvageable genesis prefix — scan keeps valid leading
    # records of a corrupt segment by design)
    corrupt_segment(jpath, segment=segment,
                    offset=8 if segment == 0 else None)
    for _ in range(300):
        rig.pump(1)
        if rig.director.hosts[victim].state == "dead":
            break
    fo = rig.director.failovers[-1]
    surv = rig.agents[0]
    assert surv.terminated is None
    if segment == 0:
        # genesis gone: typed loss, fleet keeps breathing
        assert fo["lost"] == [0] and fo["tiers"] == {}
        assert rig.director.matches[0]["state"] == "lost"
        rig.pump(5)
    else:
        # genesis prefix survives the quarantine: journal-tier recovery
        # still lands, and the finished match is bitwise the twin
        assert fo["tiers"] == {"0": "journal"} and fo["lost"] == []
        rig.drive_done(cores=[surv])
        parity = compare_with_twin(
            [spec], rig.director.collect_reports(), {0}
        )
        assert parity["clean_exact"] and parity["faulted_exact"], parity


def test_journal_disabled_agent_falls_back_to_lost(tmp_path):
    """journal=False agents behave exactly like the pre-journal fleet:
    a destroyed ticket means a lost match (the old contract), with no
    journal machinery in the failover path."""
    from test_fleet_control import _spec

    rig = _rig(tmp_path, checkpoint_every=6)
    for core in rig.agents:
        core.journal_enabled = False
        core.journal_dir = None
    spec = _spec(0, seed=44, ticks=120)
    victim = rig.director.place_match(spec)
    for _ in range(40):
        rig.pump(1)
    _kill_totally(rig, victim)
    for _ in range(300):
        rig.pump(1)
        if rig.director.hosts[victim].state == "dead":
            break
    fo = rig.director.failovers[-1]
    assert fo["lost"] == [0] and fo["tiers"] == {}
    assert fo["journal_matches"] == []
