"""Async device-resident dispatch pipeline (TpuRollbackBackend
(async_dispatch=True)): the host stays ahead of the device behind a small
in-flight fence, ticks ride fused multi-tick batches, and checksums stay
lazy futures drained in batches. None of that may change a single bit:
these tests pin the async path to the eager path through forced rollbacks,
a mid-run disconnect (the forced-rollback-with-DISCONNECTED-statuses case)
and the desync-report protocol, and pin the lazy report drain's ordering.
"""

import numpy as np
import pytest

from ggrs_tpu import (
    DesyncDetected,
    DesyncDetection,
    LoadGameState,
    PlayerType,
    SaveGameState,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.sync_layer import PendingChecksumReport
from ggrs_tpu.tpu import TpuRollbackBackend
from ggrs_tpu.utils.clock import FakeClock
from stubs import GameStub, RandomChecksumGameStub

ENTITIES = 64
PLAYERS = 2


def make_backend(async_dispatch, **kw):
    return TpuRollbackBackend(
        ExGame(num_players=PLAYERS, num_entities=ENTITIES),
        max_prediction=8,
        num_players=PLAYERS,
        async_dispatch=async_dispatch,
        **kw,
    )


def assert_states_equal(a, b):
    sa, sb = a.state_numpy(), b.state_numpy()
    for k in sa:
        np.testing.assert_array_equal(
            np.asarray(sa[k]), np.asarray(sb[k]), err_msg=f"state[{k}]"
        )


# ----------------------------------------------------------------------
# parity: SyncTest forced rollbacks
# ----------------------------------------------------------------------


def drive_synctest(backend, ticks, check_distance=4):
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(8)
        .with_check_distance(check_distance)
        .start_synctest_session()
    )
    getters = []
    for t in range(ticks):
        for h in range(PLAYERS):
            sess.add_local_input(h, bytes([(t * (3 + h) + h) % 16]))
        reqs = sess.advance_frame()
        backend.handle_requests(reqs)
        # capture per save, via getters stable across ring-slot reuse —
        # comparing cell.checksum at the end would only see the last
        # save landing in each reused cell
        getters += [
            (r.frame, r.cell.checksum_getter())
            for r in reqs
            if isinstance(r, SaveGameState)
        ]
    return [(f, g()) for f, g in getters]


def test_async_bit_parity_through_forced_rollbacks():
    """Same SyncTest request stream (a forced rollback every tick past
    check_distance) through an eager and an async backend: every saved
    checksum and the final state bit-identical. The async run's lazy
    drain happens when the getters resolve, long after the ticks."""
    eager, asynch = make_backend(False), make_backend(True)
    se = drive_synctest(eager, 30)
    sa = drive_synctest(asynch, 30)
    assert asynch.lazy_ticks == TpuRollbackBackend.ASYNC_DEFAULT_LAZY_TICKS
    assert se == sa
    assert_states_equal(eager, asynch)


def test_async_dispatch_signatures_canonicalize():
    """Repeated rollback blocks of one shape must coalesce onto a handful
    of canonical dispatch signatures (each keyed to one cached jitted
    program), not one per tick."""
    backend = make_backend(True)
    drive_synctest(backend, 40)
    sigs = backend.dispatch_signatures
    assert sum(sigs.values()) >= 40  # every segment tallied
    assert len(sigs) <= 6, f"signature explosion: {sigs}"


# ----------------------------------------------------------------------
# parity: P2P misprediction rollbacks + mid-run disconnect
# ----------------------------------------------------------------------


def run_p2p_device(async_mode, frames=60, disconnect_tick=30):
    """A deterministic 2-player P2P run: fixed network latency makes
    session 0 predict (and mispredict) remote inputs, and a mid-run
    disconnect forces the rollback-with-DISCONNECTED-statuses path. The
    whole world (clock, network, scripts) is pinned, so eager and async
    runs see identical request streams — any checksum difference is the
    backend's fault."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=40, seed=11)

    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(PLAYERS)
            .with_max_prediction_window(8)
            .with_clock(clock)
        )
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(
            PlayerType.remote(other_addr), 1 - local_handle
        )
        return b.start_p2p_session(net.socket(my_addr))

    s0, s1 = build("a", "b", 0), build("b", "a", 1)
    for _ in range(400):
        for s in (s0, s1):
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(
            s.current_state() == SessionState.RUNNING for s in (s0, s1)
        ):
            break
    else:
        raise AssertionError("sessions failed to synchronize")

    backend = make_backend(async_mode)
    peer = GameStub()
    getters = []
    saw_rollback_after_disconnect = False
    for f in range(frames):
        s0.add_local_input(0, bytes([(f * 3 + 1) % 16]))
        reqs = s0.advance_frame()
        backend.handle_requests(reqs)
        getters += [
            (r.frame, r.cell.checksum_getter())
            for r in reqs
            if isinstance(r, SaveGameState)
        ]
        if f > disconnect_tick and any(
            isinstance(r, LoadGameState) for r in reqs
        ):
            saw_rollback_after_disconnect = True
        s0.events()
        if f == disconnect_tick:
            s0.disconnect_player(1)
        if f < disconnect_tick:
            s1.add_local_input(1, bytes([(f * 5 + 2) % 16]))
            peer.handle_requests(s1.advance_frame())
            s1.events()
        clock.advance(16)
    assert saw_rollback_after_disconnect
    stream = [(f, g()) for f, g in getters]
    return stream, backend


def test_async_parity_through_disconnect_rollback():
    eager_stream, eager = run_p2p_device(False)
    async_stream, asynch = run_p2p_device(True)
    assert eager_stream == async_stream
    assert_states_equal(eager, asynch)


# ----------------------------------------------------------------------
# lazy desync-report drain: ordering + batching
# ----------------------------------------------------------------------


class FakeGetter:
    def __init__(self, value):
        self.value = value
        self.ready = False
        self.prefetches = 0

    def prefetch(self):
        self.prefetches += 1

    def __call__(self):
        return self.value


class FakeCell:
    def __init__(self, frame, getter):
        self.frame = frame
        self._getter = getter

    def checksum_getter(self):
        return self._getter


def test_pending_report_drains_in_frame_order():
    """Reports queue while their device values are in flight and drain in
    capture order — a ready report NEVER jumps an unready older one (the
    peer would see out-of-order frames), and nothing forces a sync until
    `force` bounds the delay."""
    rep = PendingChecksumReport()
    getters = {f: FakeGetter(f * 1000 + 7) for f in (10, 20, 30)}
    for f in (10, 20, 30):
        rep.capture(f, FakeCell(f, getters[f]))
    emitted = []
    emit = lambda frame, checksum: emitted.append((frame, checksum))

    rep.flush(force=False, emit=emit)
    assert emitted == []  # head in flight: nothing emitted, no sync forced
    assert getters[10].prefetches > 0  # ...but its copy was started

    getters[20].ready = True  # a LATER report landing first
    rep.flush(force=False, emit=emit)
    assert emitted == []  # must not jump the queue past frame 10

    getters[10].ready = True
    rep.flush(force=False, emit=emit)
    assert emitted == [(10, 10007), (20, 20007)]  # one batch, in order

    rep.flush(force=True, emit=emit)  # force bounds the tail's delay
    assert emitted == [(10, 10007), (20, 20007), (30, 30007)]
    assert len(rep) == 0


def test_pending_report_drops_reused_slot():
    """A report whose ring cell was overwritten before the first read is
    dropped (its checksum now belongs to a different frame); younger
    reports still drain."""
    rep = PendingChecksumReport()
    stale = FakeGetter(1)
    live = FakeGetter(2)
    rep.capture(5, FakeCell(99, stale))  # cell.frame != captured frame
    rep.capture(6, FakeCell(6, live))
    live.ready = True
    emitted = []
    rep.flush(force=False, emit=lambda f, c: emitted.append((f, c)))
    assert emitted == [(6, 2)]


def test_desync_reports_surface_on_correct_frames_async():
    """End-to-end ordering witness: session 0 fulfills on the async device
    backend, its peer publishes garbage checksums — every DesyncDetected
    event must name a frame session 0 actually reported, with the exact
    checksum its lazy drain emitted for that frame."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, seed=17)

    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(PLAYERS)
            .with_max_prediction_window(8)
            .with_desync_detection_mode(DesyncDetection.on(10))
            .with_clock(clock)
        )
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        return b.start_p2p_session(net.socket(my_addr))

    s0, s1 = build("a", "b", 0), build("b", "a", 1)
    for _ in range(400):
        for s in (s0, s1):
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(
            s.current_state() == SessionState.RUNNING for s in (s0, s1)
        ):
            break
    else:
        raise AssertionError("sessions failed to synchronize")

    backend = make_backend(True)
    peer = RandomChecksumGameStub()
    events = []
    for f in range(150):
        s0.add_local_input(0, b"\x01")
        backend.handle_requests(s0.advance_frame())
        s1.add_local_input(1, b"\x01")
        peer.handle_requests(s1.advance_frame())
        events += s0.events() + s1.events()
        clock.advance(16)
    desyncs = [e for e in events if isinstance(e, DesyncDetected)]
    assert desyncs, "random peer checksums must trip desync detection"
    history = s0.local_checksum_history
    for e in [e for e in desyncs if e.addr == "b"]:
        assert e.frame in history, (
            f"desync reported for frame {e.frame} session 0 never published"
        )
        assert e.local_checksum == history[e.frame]


# ----------------------------------------------------------------------
# plumbing: knobs survive checkpoints, composition with beam
# ----------------------------------------------------------------------


def test_async_checkpoint_roundtrip(tmp_path):
    backend = make_backend(True)
    drive_synctest(backend, 12)
    path = str(tmp_path / "async.npz")
    backend.save(path)
    restored = TpuRollbackBackend.restore(
        path, ExGame(num_players=PLAYERS, num_entities=ENTITIES)
    )
    assert restored.async_dispatch
    assert restored.lazy_ticks == backend.lazy_ticks
    assert restored.async_inflight == backend.async_inflight
    assert_states_equal(restored, backend)


def test_async_composes_with_beam():
    """Speculation adoption flushes the pending batch before anchoring;
    the fence must not deadlock or reorder around it."""
    asynch = make_backend(True, beam_width=8)
    eager = make_backend(False)
    se = drive_synctest(eager, 30)
    sa = drive_synctest(asynch, 30)
    assert se == sa
    assert_states_equal(eager, asynch)
    assert asynch.beam_hits + asynch.beam_partial_hits + asynch.beam_misses > 0
