"""The history-informed candidate model (ggrs_tpu/tpu/input_model.py).

The reference's prediction floor is repeat-last
(/root/reference/src/input_queue.rs:126-139); the beam's branch members
exist to beat it. These tests pin the model's two learned distributions
(hold-length hazard, value transitions), the likelihood ranking they
produce, the branching_beam prediction stream that consumes it, and the
end-to-end payoff: a NARROW beam adopting mid-window toggles that the
uniform offset sweep cannot cover at that width.
"""

import numpy as np

from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.tpu import TpuRollbackBackend
from ggrs_tpu.tpu.beam import branching_beam
from ggrs_tpu.tpu.input_model import InputHistoryModel

from test_beam_backend import drive_synctest_pair, make_backend

PLAYERS = 2
ENTITIES = 64


def feed_toggle(model, player, a=5, b=9, hold=6, cycles=6):
    """hold frames of a, hold of b, repeated."""
    for _ in range(cycles):
        for _ in range(hold):
            model.observe(player, bytes([a]))
        for _ in range(hold):
            model.observe(player, bytes([b]))


def test_model_learns_holds_and_transitions():
    m = InputHistoryModel(PLAYERS, 1)
    feed_toggle(m, 0, hold=6)
    st = m._stats[0]
    assert st.n_holds() >= 8
    # the hazard must spike at the true hold length...
    assert st.hazard(6) > 0.7
    # ...and stay low just before it
    assert st.hazard(4) < 0.2
    # transitions: from 5 the only observed successor is 9 (and vice versa)
    assert st.next_values(bytes([5]))[0][0] == bytes([9])
    assert st.next_values(bytes([9]))[0][0] == bytes([5])


def test_model_break_run_severs_without_recording():
    m = InputHistoryModel(1, 1)
    for _ in range(5):
        m.observe(0, bytes([3]))
    m.break_run(0)
    # the severed run must not have produced a 5-frame hold record or a
    # transition
    assert m._stats[0].n_holds() == 0
    assert m._stats[0].next_values(bytes([3])) == []
    # and the next value starts a fresh run
    m.observe(0, bytes([7]))
    assert m._stats[0].cur_value == bytes([7])
    assert m._stats[0].cur_len == 1


def test_rank_branches_puts_true_switch_first():
    m = InputHistoryModel(PLAYERS, 1)
    feed_toggle(m, 0, a=5, b=9, hold=6)
    # player 0 confirmed through frame 99, holding 5 for 4 frames: with
    # hold=6 learned, frames 100-101 complete the hold and the first frame
    # of 9 is frame 102. anchor at frame 98 => beam row offset 4.
    confirmed = [(99, bytes([5]), 4), None]
    preds = m.rank_branches(confirmed, anchor_frame=98, rollout=8, limit=6)
    assert preds, "model with history must emit candidates"
    p, offset, row = preds[0]
    assert (p, offset) == (0, 4) and row[0] == 9
    # a player with no signal emits nothing
    assert all(pp == 0 for pp, _, _ in preds)


def feed_holds(model, player, lengths, a=5, b=9):
    """Alternate two values, holding each for the next length in
    `lengths`; a trailing observe closes the final run so every length
    is recorded."""
    vals = (a, b)
    for i, ln in enumerate(lengths):
        for _ in range(ln):
            model.observe(player, bytes([vals[i % 2]]))
    model.observe(player, bytes([vals[len(lengths) % 2]]))


def test_rank_branches_survival_discount_beats_raw_hazard():
    """The exact switch-at-offset-d score is hazard(run+d-1) times the
    SURVIVAL product over the intervening frames. This distribution
    makes the two orderings disagree: hazard peaks at hold length 6
    (raw-hazard ranking would bet on the later offset first), but
    enough mass switches at 5 that surviving past it is unlikely — the
    exact score puts the EARLIER offset first. Pinned so the survival
    factor can't silently regress back to the pre-PR-18 approximation."""
    m = InputHistoryModel(1, 1)
    # hold_counts {5: 10, 6: 8}: h(5) ~= 0.538, h(6) ~= 0.895
    feed_holds(m, 0, [5] * 10 + [6] * 8)
    st = m._stats[0]
    # raw hazard prefers the LATER offset...
    assert st.hazard(6) > st.hazard(5) > 0.4
    # ...but the survival-discounted score prefers the earlier one
    assert st.hazard(5) > st.hazard(6) * (1.0 - st.hazard(5))
    # run=5 at the frontier: offset 1 completes hold 5, offset 2 hold 6
    preds = m.rank_branches([(100, bytes([5]), 5)], 100, 8, 6)
    offsets = [off for _p, off, _row in preds]
    assert offsets[:2] == [1, 2], offsets
    assert all(row[0] == 9 for _p, _off, row in preds)


def test_rank_branches_respects_rollout_bounds():
    m = InputHistoryModel(1, 1)
    feed_toggle(m, 0, hold=6)
    # frontier far behind the anchor: every candidate offset would be
    # negative => nothing emitted rather than a clamped lie
    preds = m.rank_branches([(10, bytes([5]), 6)], 30, 4, 8)
    assert preds == []


def test_branching_beam_prediction_stream_joint_first():
    last = np.array([[5], [9]], dtype=np.uint8)
    prev = np.array([[0], [0]], dtype=np.uint8)
    preds = [
        (0, 2, np.array([7], dtype=np.uint8)),
        (1, 4, np.array([3], dtype=np.uint8)),
    ]
    beam = branching_beam(
        last, prev, window=6, beam_width=8, predictions=preds
    )
    # member 0 stays repeat-last
    assert (beam[0, :, 0, 0] == 5).all() and (beam[0, :, 1, 0] == 9).all()
    # member 1 is the JOINT future: both players' top-ranked switches
    assert (beam[1, :2, 0, 0] == 5).all() and (beam[1, 2:, 0, 0] == 7).all()
    assert (beam[1, :4, 1, 0] == 9).all() and (beam[1, 4:, 1, 0] == 3).all()
    # each individual spec also gets a member
    w0 = np.array([5, 5, 7, 7, 7, 7], dtype=np.uint8)
    assert any(
        np.array_equal(beam[b, :, 0, 0], w0) and (beam[b, :, 1, 0] == 9).all()
        for b in range(8)
    )
    w1 = np.array([9, 9, 9, 9, 3, 3], dtype=np.uint8)
    assert any(
        np.array_equal(beam[b, :, 1, 0], w1) and (beam[b, :, 0, 0] == 5).all()
        for b in range(8)
    )


def test_branching_beam_cold_model_unchanged():
    """predictions=None must reproduce the pre-model generator exactly."""
    last = np.array([[5], [9]], dtype=np.uint8)
    prev = np.array([[5], [2]], dtype=np.uint8)
    a = branching_beam(last, prev, window=6, beam_width=16)
    b = branching_beam(
        last, prev, window=6, beam_width=16, predictions=None
    )
    assert np.array_equal(a, b)


def test_narrow_beam_adopts_with_model_ranking():
    """The payoff case: at beam_width=4 the uniform sweep only covers
    switch offsets 0-1 (three branch members round-robined over three
    streams), so a 6-frame-hold toggle whose switches land across the
    whole 4-frame rollback window mostly misses. The model learns the
    hold length within a few cycles and the joint prediction member nails
    the exact switch offset — a majority of rollbacks must adopt, while
    staying bit-identical to plain resimulation (drive_synctest_pair
    asserts states every tick)."""
    beam, plain = make_backend(beam_width=4), make_backend(beam_width=0)
    script = lambda t, h: bytes([(5 if (t // 6) % 2 == 0 else 9) + h])
    drive_synctest_pair(beam, plain, script, ticks=60)
    adopted = beam.beam_hits + beam.beam_partial_hits
    assert adopted > beam.beam_misses, (
        beam.beam_hits, beam.beam_partial_hits, beam.beam_misses,
    )
    # the model actually observed finalized history (not just cold)
    assert beam.input_model._stats[0].n_holds() >= 3


def test_model_feeds_only_finalized_frames():
    """Frames inside the rollback window must not enter the statistics:
    the backend's _finalized_to pointer trails current_frame by
    max_prediction."""
    backend = make_backend(beam_width=4)
    sess_inputs = lambda t, h: bytes([t % 3])
    from test_beam_backend import make_synctest

    sess = make_synctest()
    for t in range(20):
        for h in range(PLAYERS):
            sess.add_local_input(h, sess_inputs(t, h))
        backend.handle_requests(sess.advance_frame())
    assert backend._finalized_to == backend.current_frame - 7, (
        backend._finalized_to, backend.current_frame,
    )
