"""Boundary coverage for the input-POD contract: wide inputs (up to the
native 64-byte cap) and wide sessions (up to the native 16-player cap)
through queues, compression, wire and both session stacks.

The reference's Input is any POD (src/lib.rs:250-255); here it is a fixed
byte string per player per frame. Most tests use 1 byte — these pin the
edges, where stride bugs in the delta/RLE codec, the per-player re-split
(InputBytes.to_player_inputs analog) and the native fixed-size buffers
would hide.
"""

import random

import pytest

from ggrs_tpu import (
    AdvanceFrame,
    InputStatus,
    LoadGameState,
    PlayerType,
    SaveGameState,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.errors import InvalidRequest
from ggrs_tpu.native import available
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.utils.clock import FakeClock

NATIVE_PARAMS = [False] + ([True] if available() else [])


class WideGameStub:
    """Deterministic state machine over arbitrary-width inputs."""

    def __init__(self):
        self.frame = 0
        self.state = 0
        self.history = {}

    def handle_requests(self, requests):
        for req in requests:
            if isinstance(req, SaveGameState):
                req.cell.save(req.frame, (self.frame, self.state), self.state)
            elif isinstance(req, LoadGameState):
                self.frame, self.state = req.cell.load()
            elif isinstance(req, AdvanceFrame):
                self.frame += 1
                for buf, status in req.inputs:
                    if status != InputStatus.DISCONNECTED:
                        self.state = (
                            self.state * 31 + sum(buf) + len(buf)
                        ) % (1 << 53)
                    else:
                        self.state = (self.state * 31 + 13) % (1 << 53)
                self.history[self.frame] = self.state


def wide_input(frame, handle, size, salt=0):
    rng = random.Random((frame * 131 + handle) * 977 + salt)
    return bytes(rng.randrange(256) for _ in range(size))


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
@pytest.mark.parametrize("input_size", [4, 64])
def test_wide_inputs_p2p_convergence(use_native, input_size):
    """Max-width inputs cross the delta+RLE wire under latency and jitter;
    replicas converge byte-exactly."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=40, jitter_ms=15, seed=3)

    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=input_size)
            .with_num_players(2)
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        if use_native:
            b = b.with_native_sessions(True)
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        return b.start_p2p_session(net.socket(my_addr))

    s0, s1 = build("a", "b", 0), build("b", "a", 1)
    for _ in range(400):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(20)
        if (
            s0.current_state() == SessionState.RUNNING
            and s1.current_state() == SessionState.RUNNING
        ):
            break
    g0, g1 = WideGameStub(), WideGameStub()
    for frame in range(50):
        s0.add_local_input(0, wide_input(frame, 0, input_size))
        g0.handle_requests(s0.advance_frame())
        s1.add_local_input(1, wide_input(frame, 1, input_size))
        g1.handle_requests(s1.advance_frame())
        s0.events()
        s1.events()
        clock.advance(16)
    for _ in range(10):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(16)
    s0.add_local_input(0, bytes(input_size))
    g0.handle_requests(s0.advance_frame())
    s1.add_local_input(1, bytes(input_size))
    g1.handle_requests(s1.advance_frame())

    confirmed = min(s0.confirmed_frame(), s1.confirmed_frame())
    assert confirmed > 25
    for f in range(1, confirmed + 1):
        assert g0.history[f] == g1.history[f], f"diverged at frame {f}"


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
def test_sixteen_player_synctest(use_native):
    """The native cap: 16 players, multi-byte inputs, forced rollbacks."""
    players, input_size = 16, 8
    b = (
        SessionBuilder(input_size=input_size)
        .with_num_players(players)
        .with_check_distance(3)
    )
    if use_native:
        b = b.with_native_sessions(True)
    sess = b.start_synctest_session()
    g = WideGameStub()
    for frame in range(25):
        for h in range(players):
            sess.add_local_input(h, wide_input(frame, h, input_size))
        g.handle_requests(sess.advance_frame())
    assert g.frame == 25


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
def test_eight_player_mesh_wide_inputs(use_native):
    """8 sessions x 8-byte inputs over one network: every peer confirms an
    identical prefix (full-mesh analog of the reference's 2-session test)."""
    players, input_size = 8, 8
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=10, seed=5)
    addrs = [f"p{i}" for i in range(players)]

    def build(i):
        b = (
            SessionBuilder(input_size=input_size)
            .with_num_players(players)
            .with_clock(clock)
            .with_rng(random.Random(i + 1))
        )
        if use_native:
            b = b.with_native_sessions(True)
        for h in range(players):
            b = b.add_player(
                PlayerType.local() if h == i else PlayerType.remote(addrs[h]), h
            )
        return b.start_p2p_session(net.socket(addrs[i]))

    sessions = [build(i) for i in range(players)]
    for _ in range(600):
        for s in sessions:
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(s.current_state() == SessionState.RUNNING for s in sessions):
            break
    else:
        raise AssertionError("mesh failed to synchronize")

    stubs = [WideGameStub() for _ in range(players)]
    for frame in range(20):
        for i, (s, g) in enumerate(zip(sessions, stubs)):
            s.add_local_input(i, wide_input(frame, i, input_size))
            g.handle_requests(s.advance_frame())
            s.events()
        clock.advance(16)
    for _ in range(10):
        for s in sessions:
            s.poll_remote_clients()
        clock.advance(16)
    for i, (s, g) in enumerate(zip(sessions, stubs)):
        s.add_local_input(i, bytes(input_size))
        g.handle_requests(s.advance_frame())

    confirmed = min(s.confirmed_frame() for s in sessions)
    assert confirmed > 8
    for f in range(1, confirmed + 1):
        vals = {g.history[f] for g in stubs}
        assert len(vals) == 1, f"mesh diverged at frame {f}: {vals}"


def test_native_rejects_oversized_inputs():
    if not available():
        pytest.skip("native library not built")
    with pytest.raises(InvalidRequest):
        SessionBuilder(input_size=65).with_native_sessions(True)
    with pytest.raises(InvalidRequest):
        (
            SessionBuilder(input_size=1)
            .with_num_players(17)
            .with_native_sessions(True)
            .start_synctest_session()
        )
