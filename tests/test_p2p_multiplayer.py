"""Multi-player configurations: 4-player sessions with the 12-frame window
on the device backend (BASELINE.json configs[3]), shared-address endpoints
(several remote handles behind one peer), and time-sync wait
recommendations."""

import random

import numpy as np

from ggrs_tpu import (
    PlayerType,
    SessionBuilder,
    SessionState,
    WaitRecommendation,
)
from ggrs_tpu.models import ex_game
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.utils.clock import FakeClock
from stubs import GameStub


def sync_sessions(sessions, clock):
    for _ in range(400):
        for s in sessions:
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(s.current_state() == SessionState.RUNNING for s in sessions):
            return
    raise AssertionError("sessions failed to synchronize")


def build_4p(clock, net, max_prediction=12):
    """Four sessions, one local player each, full mesh."""
    addrs = ["a", "b", "c", "d"]
    sessions = []
    for i, my in enumerate(addrs):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(4)
            .with_max_prediction_window(max_prediction)
            .with_clock(clock)
            .with_rng(random.Random(100 + i))
        )
        for h, addr in enumerate(addrs):
            if h == i:
                b = b.add_player(PlayerType.local(), h)
            else:
                b = b.add_player(PlayerType.remote(addr), h)
        sessions.append(b.start_p2p_session(net.socket(my)))
    return sessions


def test_four_player_mesh_with_device_backend():
    """configs[3]: 4-player session, 12-frame rollback window, one peer on
    the TpuRollbackBackend, others on host stubs; confirmed prefixes agree."""
    from ggrs_tpu.tpu import TpuRollbackBackend

    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=40, jitter_ms=15, seed=33)
    sessions = build_4p(clock, net, max_prediction=12)
    sync_sessions(sessions, clock)

    backend = TpuRollbackBackend(
        ex_game.ExGame(num_players=4, num_entities=64),
        max_prediction=12,
        num_players=4,
    )
    stubs = [GameStub() for _ in range(3)]
    handlers = [backend] + stubs

    for frame in range(60):
        for i, sess in enumerate(sessions):
            sess.add_local_input(i, bytes([(frame * (i + 2) + i) % 16]))
            handlers[i].handle_requests(sess.advance_frame())
            sess.events()
        clock.advance(16)

    for _ in range(10):
        for s in sessions:
            s.poll_remote_clients()
        clock.advance(16)
    for i, sess in enumerate(sessions):
        sess.add_local_input(i, b"\x00")
        handlers[i].handle_requests(sess.advance_frame())

    confirmed = min(s.confirmed_frame() for s in sessions)
    assert confirmed > 30
    # all three stub replicas agree on the confirmed prefix
    for f in range(1, confirmed + 1):
        vals = {g.history[f] for g in stubs}
        assert len(vals) == 1, f"stub replicas diverged at frame {f}"
    # the device peer reached the same frame count
    assert int(backend.state_numpy()["frame"]) == 61
    # rollbacks actually exercised the 12-frame window path
    assert any(g.loaded_frames for g in stubs)


def test_two_remote_players_share_one_endpoint():
    """One machine hosts two players: the other session groups both handles
    behind a single endpoint (builder.rs:276-293) and inputs for both arrive
    interleaved from one address."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, seed=5)

    # session A: locals 0,1; remote 2 at "b"
    a = (
        SessionBuilder(input_size=1)
        .with_num_players(3)
        .with_clock(clock)
        .with_rng(random.Random(1))
        .add_player(PlayerType.local(), 0)
        .add_player(PlayerType.local(), 1)
        .add_player(PlayerType.remote("b"), 2)
        .start_p2p_session(net.socket("a"))
    )
    # session B: local 2; remotes 0,1 both at "a" -> ONE endpoint
    b = (
        SessionBuilder(input_size=1)
        .with_num_players(3)
        .with_clock(clock)
        .with_rng(random.Random(2))
        .add_player(PlayerType.remote("a"), 0)
        .add_player(PlayerType.remote("a"), 1)
        .add_player(PlayerType.local(), 2)
        .start_p2p_session(net.socket("b"))
    )
    assert len(b.player_reg.remotes) == 1
    assert b.player_reg.remotes["a"].handles == [0, 1]

    sync_sessions([a, b], clock)
    ga, gb = GameStub(), GameStub()
    for frame in range(40):
        a.add_local_input(0, bytes([frame % 4]))
        a.add_local_input(1, bytes([frame % 6]))
        ga.handle_requests(a.advance_frame())
        b.add_local_input(2, bytes([frame % 5]))
        gb.handle_requests(b.advance_frame())
        clock.advance(16)

    for _ in range(6):
        a.poll_remote_clients()
        b.poll_remote_clients()
        clock.advance(16)
    a.add_local_input(0, b"\x00")
    a.add_local_input(1, b"\x00")
    ga.handle_requests(a.advance_frame())
    b.add_local_input(2, b"\x00")
    gb.handle_requests(b.advance_frame())

    confirmed = min(a.confirmed_frame(), b.confirmed_frame())
    assert confirmed > 20
    for f in range(1, confirmed + 1):
        assert ga.history[f] == gb.history[f]


def test_wait_recommendation_for_fast_peer():
    """A session running far ahead of its remote gets WaitRecommendation
    events (p2p_session.rs:763-776)."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, seed=6)
    fast = (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_max_prediction_window(8)
        .with_clock(clock)
        .with_rng(random.Random(11))
        .add_player(PlayerType.local(), 0)
        .add_player(PlayerType.remote("slow"), 1)
        .start_p2p_session(net.socket("fast"))
    )
    slow = (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_max_prediction_window(8)
        .with_clock(clock)
        .with_rng(random.Random(12))
        .add_player(PlayerType.remote("fast"), 0)
        .add_player(PlayerType.local(), 1)
        .start_p2p_session(net.socket("slow"))
    )
    sync_sessions([fast, slow], clock)

    from ggrs_tpu import PredictionThreshold

    g_fast, g_slow = GameStub(), GameStub()
    events = []
    skipped = 0
    slow_frame = 0
    for frame in range(120):
        try:
            fast.add_local_input(0, b"\x01")
            g_fast.handle_requests(fast.advance_frame())
        except PredictionThreshold:
            skipped += 1  # the app skips a frame (ex_game_p2p.rs:115-117)
        events += fast.events()
        # the slow peer advances every 4th frame only
        if frame % 4 == 0:
            slow.add_local_input(1, b"\x01")
            g_slow.handle_requests(slow.advance_frame())
            slow_frame += 1
        else:
            slow.poll_remote_clients()
        clock.advance(16)

    recs = [e for e in events if isinstance(e, WaitRecommendation)]
    assert recs, "fast peer never told to wait"
    assert all(r.skip_frames >= 3 for r in recs)
    # the prediction-threshold backpressure also kicked in
    assert skipped > 0
