"""C++ InputQueue parity: random operation sequences must behave identically
to the Python oracle (same outputs, same errors, same internal watermarks)."""

import random

import pytest

from ggrs_tpu.frame_info import PlayerInput
from ggrs_tpu.input_queue import InputQueue


@pytest.fixture(scope="module")
def native_queue_cls():
    from ggrs_tpu import native as nat
    from ggrs_tpu.native.build import build

    if not nat.available():
        if not build():
            pytest.skip("no native toolchain")
        nat._load_attempted = False
    if not nat.available():
        pytest.fail("native library built but failed to load")
    from ggrs_tpu.native.input_queue import NativeInputQueue

    return NativeInputQueue


def run_both(py_q, nat_q, op, *args):
    """Apply an operation to both queues; both must agree on result or both
    must fail."""
    results = []
    for q in (py_q, nat_q):
        try:
            results.append(("ok", getattr(q, op)(*args)))
        except AssertionError:
            results.append(("err", None))
    (k1, v1), (k2, v2) = results
    assert k1 == k2, f"{op}{args}: python={k1}, native={k2}"
    if k1 == "ok":
        if op == "confirmed_input":
            assert v1.buf == v2.buf and v1.frame == v2.frame
        else:
            assert v1 == v2, f"{op}{args}: {v1} != {v2}"


def check_watermarks(py_q, nat_q):
    assert py_q.first_incorrect_frame == nat_q.first_incorrect_frame
    assert py_q.last_added_frame == nat_q.last_added_frame
    assert py_q.length == nat_q.length


@pytest.mark.parametrize("input_size", [1, 4])
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_operation_sequences(native_queue_cls, input_size, seed):
    rng = random.Random(seed)
    py_q = InputQueue(input_size)
    nat_q = native_queue_cls(input_size)

    next_frame = 0
    for _ in range(600):
        op = rng.random()
        if op < 0.45:
            buf = bytes(rng.randrange(4) for _ in range(input_size))
            run_both(py_q, nat_q, "add_input", PlayerInput(next_frame, buf))
            if py_q.last_added_frame != -1:
                next_frame += 1
        elif op < 0.8:
            # fetch near the frontier: confirmed or prediction
            target = max(0, next_frame - rng.randrange(0, 4) + rng.randrange(0, 3))
            if py_q.first_incorrect_frame == -1:
                run_both(py_q, nat_q, "input", target)
        elif op < 0.88:
            run_both(py_q, nat_q, "reset_prediction")
        elif op < 0.95:
            if py_q.last_added_frame > 2:
                frame = rng.randrange(0, py_q.last_added_frame)
                run_both(py_q, nat_q, "discard_confirmed_frames", frame)
        else:
            if py_q.last_added_frame >= 0:
                run_both(py_q, nat_q, "confirmed_input", py_q.last_added_frame)
        check_watermarks(py_q, nat_q)


def test_frame_delay_parity(native_queue_cls):
    for delay in (0, 2, 5):
        py_q = InputQueue(1)
        nat_q = native_queue_cls(1)
        py_q.set_frame_delay(delay)
        nat_q.set_frame_delay(delay)
        for i in range(30):
            run_both(py_q, nat_q, "add_input", PlayerInput(i, bytes([i % 7])))
            run_both(py_q, nat_q, "input", i)
            check_watermarks(py_q, nat_q)


def test_misprediction_detection_parity(native_queue_cls):
    py_q = InputQueue(1)
    nat_q = native_queue_cls(1)
    for q in (py_q, nat_q):
        q.add_input(PlayerInput(0, b"\x07"))
        q.input(1)  # predict 7
        q.input(2)
        q.add_input(PlayerInput(1, b"\x09"))  # wrong prediction
    assert py_q.first_incorrect_frame == nat_q.first_incorrect_frame == 1
    for q in (py_q, nat_q):
        q.reset_prediction()
    run_both(py_q, nat_q, "input", 1)
    check_watermarks(py_q, nat_q)


def test_session_with_native_queues_matches_python_queues(native_queue_cls):
    """A full SyncTest session run must be byte-identical between queue
    implementations (same request stream, same stub evolution)."""
    from ggrs_tpu import SessionBuilder
    from stubs import GameStub

    def run(native):
        sess = (
            SessionBuilder(input_size=2)
            .with_num_players(2)
            .with_check_distance(3)
            .with_input_delay(1)
            .with_native_input_queues(native)
            .start_synctest_session()
        )
        stub = GameStub()
        for frame in range(120):
            for h in range(2):
                sess.add_local_input(h, bytes([frame % 9, (frame * 3 + h) % 5]))
            stub.handle_requests(sess.advance_frame())
        return stub

    a = run(False)
    b = run(True)
    assert a.gs.frame == b.gs.frame
    assert a.gs.state == b.gs.state
    assert a.history == b.history
    assert a.saved_frames == b.saved_frames
    assert a.loaded_frames == b.loaded_frames
