"""Parity tests for the native C++ session core (native/session.cpp).

The Python sessions are the behavioral oracles: identical input scripts over
identical (deterministic, fault-injecting) virtual networks must produce
identical ordered request streams and identical replica histories from the
native and Python stacks. Wire compatibility is also exercised with mixed
native/Python peers on one network.
"""

import random

import pytest

from ggrs_tpu import (
    AdvanceFrame,
    DesyncDetected,
    DesyncDetection,
    Disconnected,
    LoadGameState,
    MismatchedChecksum,
    NetworkInterrupted,
    NotSynchronized,
    PlayerType,
    SaveGameState,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.native import available
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.utils.clock import FakeClock
from stubs import GameStub, RandomChecksumGameStub

pytestmark = pytest.mark.skipif(
    not available(), reason="native library not built (make -C native)"
)


def req_sig(requests):
    """Comparable signature of an ordered request list."""
    sig = []
    for r in requests:
        if isinstance(r, SaveGameState):
            sig.append(("save", r.frame))
        elif isinstance(r, LoadGameState):
            sig.append(("load", r.frame))
        elif isinstance(r, AdvanceFrame):
            sig.append(
                ("advance", tuple((bytes(b), int(s)) for b, s in r.inputs))
            )
        else:
            raise TypeError(r)
    return sig


# ---------------------------------------------------------------------------
# SyncTest
# ---------------------------------------------------------------------------


def make_synctest(native, check_distance=4, input_delay=0, num_players=2):
    b = (
        SessionBuilder(input_size=1)
        .with_num_players(num_players)
        .with_check_distance(check_distance)
        .with_input_delay(input_delay)
    )
    if native:
        b = b.with_native_sessions(True)
    return b.start_synctest_session()


@pytest.mark.parametrize("input_delay", [0, 2])
def test_native_synctest_request_parity(input_delay):
    py = make_synctest(native=False, input_delay=input_delay)
    nat = make_synctest(native=True, input_delay=input_delay)
    g_py, g_nat = GameStub(), GameStub()
    for frame in range(40):
        for handle in range(2):
            inp = bytes([(frame * (handle + 3) + handle) % 7])
            py.add_local_input(handle, inp)
            nat.add_local_input(handle, inp)
        r_py = py.advance_frame()
        r_nat = nat.advance_frame()
        assert req_sig(r_py) == req_sig(r_nat), f"tick {frame} diverged"
        g_py.handle_requests(r_py)
        g_nat.handle_requests(r_nat)
    assert g_py.history == g_nat.history
    assert g_py.gs == g_nat.gs


def test_native_synctest_detects_random_checksums():
    nat = make_synctest(native=True, check_distance=2)
    g = RandomChecksumGameStub()
    with pytest.raises(MismatchedChecksum):
        for frame in range(20):
            nat.add_local_input(0, b"\x01")
            nat.add_local_input(1, b"\x02")
            g.handle_requests(nat.advance_frame())


def test_native_synctest_deferred_verification():
    b = (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_check_distance(2)
        .with_deferred_checksum_verification(4)
        .with_native_sessions(True)
    )
    nat = b.start_synctest_session()
    g = GameStub()
    for frame in range(30):
        nat.add_local_input(0, bytes([frame % 3]))
        nat.add_local_input(1, bytes([frame % 5]))
        g.handle_requests(nat.advance_frame())
    nat.flush_checksum_checks()

    # negative control: mismatches surface, at most `lag` ticks late
    b2 = (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_check_distance(2)
        .with_deferred_checksum_verification(4)
        .with_native_sessions(True)
    )
    bad = b2.start_synctest_session()
    g2 = RandomChecksumGameStub()
    with pytest.raises(MismatchedChecksum):
        for frame in range(30):
            bad.add_local_input(0, b"\x01")
            bad.add_local_input(1, b"\x02")
            g2.handle_requests(bad.advance_frame())
        bad.flush_checksum_checks()


# ---------------------------------------------------------------------------
# P2P
# ---------------------------------------------------------------------------


def build_pair(clock, net, *, native=(True, True), desync=None, input_delay=0,
               sparse=False, max_prediction=8):
    def build(my_addr, other_addr, local_handle, use_native):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(max_prediction)
            .with_input_delay(input_delay)
            .with_sparse_saving_mode(sparse)
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        if desync is not None:
            b = b.with_desync_detection_mode(desync)
        if use_native:
            b = b.with_native_sessions(True)
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        return b.start_p2p_session(net.socket(my_addr))

    return build("a", "b", 0, native[0]), build("b", "a", 1, native[1])


def sync_sessions(sessions, clock, iterations=400):
    for _ in range(iterations):
        for s in sessions:
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(s.current_state() == SessionState.RUNNING for s in sessions):
            return
    raise AssertionError("sessions failed to synchronize")


def drive_pair(s1, s2, g1, g2, clock, frames):
    for frame in range(frames):
        s1.add_local_input(0, bytes([(frame * 7 + 1) % 16]))
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, bytes([(frame * 5 + 2) % 16]))
        g2.handle_requests(s2.advance_frame())
        s1.events()
        s2.events()
        clock.advance(16)
    for _ in range(10):
        s1.poll_remote_clients()
        s2.poll_remote_clients()
        clock.advance(16)
    s1.add_local_input(0, b"\x00")
    g1.handle_requests(s1.advance_frame())
    s2.add_local_input(1, b"\x00")
    g2.handle_requests(s2.advance_frame())


def assert_confirmed_prefix_equal(s1, s2, g1, g2, frames):
    confirmed = min(s1.confirmed_frame(), s2.confirmed_frame())
    assert confirmed > frames // 2, "sessions never confirmed enough frames"
    for f in range(1, confirmed + 1):
        assert g1.history[f] == g2.history[f], f"replicas diverged at frame {f}"


def test_native_p2p_not_synchronized_before_handshake():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    s1, _s2 = build_pair(clock, net)
    s1.add_local_input(0, b"\x00")
    with pytest.raises(NotSynchronized):
        s1.advance_frame()


@pytest.mark.parametrize(
    "latency,jitter,loss,seed",
    [(0, 0, 0.0, 1), (50, 20, 0.0, 5), (30, 30, 0.2, 11)],
)
def test_native_p2p_replicas_converge(latency, jitter, loss, seed):
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=latency, jitter_ms=jitter,
                          loss=loss, seed=seed)
    s1, s2 = build_pair(clock, net)
    sync_sessions([s1, s2], clock)
    g1, g2 = GameStub(), GameStub()
    drive_pair(s1, s2, g1, g2, clock, 60)
    assert_confirmed_prefix_equal(s1, s2, g1, g2, 60)
    if latency >= 50:
        assert g1.loaded_frames or g2.loaded_frames, "expected rollbacks"


def test_native_python_mixed_pair_interop():
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=40, jitter_ms=10, seed=7)
    s1, s2 = build_pair(clock, net, native=(True, False))
    sync_sessions([s1, s2], clock)
    g1, g2 = GameStub(), GameStub()
    drive_pair(s1, s2, g1, g2, clock, 60)
    assert_confirmed_prefix_equal(s1, s2, g1, g2, 60)


@pytest.mark.parametrize(
    "latency,jitter,loss,seed,input_delay,sparse",
    [
        (0, 0, 0.0, 1, 0, False),
        (50, 20, 0.0, 5, 0, False),
        (30, 30, 0.2, 11, 0, False),
        (40, 0, 0.0, 3, 2, False),
        (50, 20, 0.0, 9, 0, True),
    ],
)
def test_native_p2p_request_stream_parity_vs_python(
    latency, jitter, loss, seed, input_delay, sparse
):
    """The strongest oracle: the same deterministic world (clock, fault
    seeds, inputs) must yield the exact same ordered request stream from the
    native pair as from the Python pair, tick for tick."""
    streams = []
    for use_native in (False, True):
        clock = FakeClock()
        net = InMemoryNetwork(clock, latency_ms=latency, jitter_ms=jitter,
                              loss=loss, seed=seed)
        s1, s2 = build_pair(clock, net, native=(use_native, use_native),
                            input_delay=input_delay, sparse=sparse)
        sync_sessions([s1, s2], clock)
        g1, g2 = GameStub(), GameStub()
        stream = []
        for frame in range(50):
            s1.add_local_input(0, bytes([(frame * 7 + 1) % 16]))
            r1 = s1.advance_frame()
            s2.add_local_input(1, bytes([(frame * 5 + 2) % 16]))
            r2 = s2.advance_frame()
            status_sig = tuple(
                (st.disconnected, st.last_frame) for st in s1.local_connect_status
            )
            stream.append(
                (req_sig(r1), req_sig(r2), status_sig, s1.last_saved_frame)
            )
            g1.handle_requests(r1)
            g2.handle_requests(r2)
            clock.advance(16)
        streams.append(stream)

    py_stream, nat_stream = streams
    for tick, (py_t, nat_t) in enumerate(zip(py_stream, nat_stream)):
        assert py_t == nat_t, f"request streams diverged at tick {tick}"


def test_native_p2p_desync_detection():
    clock = FakeClock()
    net = InMemoryNetwork(clock, seed=17)
    s1, s2 = build_pair(clock, net, desync=DesyncDetection.on(10))
    sync_sessions([s1, s2], clock)
    g1 = GameStub()
    g2 = RandomChecksumGameStub()  # checksums will never agree

    events = []
    for frame in range(150):
        s1.add_local_input(0, b"\x01")
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, b"\x01")
        g2.handle_requests(s2.advance_frame())
        events += s1.events() + s2.events()
        clock.advance(16)
    assert [e for e in events if isinstance(e, DesyncDetected)]


def test_native_p2p_no_false_desyncs():
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=40, jitter_ms=10, seed=13)
    s1, s2 = build_pair(clock, net, desync=DesyncDetection.on(10))
    sync_sessions([s1, s2], clock)
    g1, g2 = GameStub(), GameStub()
    events = []
    for frame in range(120):
        s1.add_local_input(0, bytes([frame % 4]))
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, bytes([frame % 6]))
        g2.handle_requests(s2.advance_frame())
        events += s1.events() + s2.events()
        clock.advance(16)
    assert not [e for e in events if isinstance(e, DesyncDetected)]


def test_native_p2p_disconnect_player_and_continue():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    s1, s2 = build_pair(clock, net)
    sync_sessions([s1, s2], clock)
    g1 = GameStub()
    for frame in range(5):
        s1.add_local_input(0, b"\x01")
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, b"\x01")
        s2.advance_frame()
        clock.advance(16)

    s1.disconnect_player(1)
    from ggrs_tpu import InvalidRequest

    with pytest.raises(InvalidRequest):
        s1.disconnect_player(1)  # already disconnected
    with pytest.raises(InvalidRequest):
        s1.disconnect_player(0)  # local player

    for frame in range(10):
        s1.add_local_input(0, b"\x01")
        g1.handle_requests(s1.advance_frame())
        clock.advance(16)
    assert s1.current_frame == 15


def test_native_p2p_timeout_disconnect_via_silence():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    s1, s2 = build_pair(clock, net)
    sync_sessions([s1, s2], clock)
    g1 = GameStub()
    for frame in range(3):
        s1.add_local_input(0, b"\x01")
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, b"\x01")
        s2.advance_frame()
        clock.advance(16)

    events = []
    for _ in range(30):
        s1.poll_remote_clients()
        events += s1.events()
        clock.advance(100)
    assert [e for e in events if isinstance(e, NetworkInterrupted)]
    assert [e for e in events if isinstance(e, Disconnected)]

    for frame in range(5):
        s1.add_local_input(0, b"\x01")
        g1.handle_requests(s1.advance_frame())
        clock.advance(16)


def test_native_p2p_network_stats_shape():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    s1, s2 = build_pair(clock, net)
    sync_sessions([s1, s2], clock)
    g1, g2 = GameStub(), GameStub()
    for frame in range(10):
        s1.add_local_input(0, b"\x01")
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, b"\x01")
        g2.handle_requests(s2.advance_frame())
        clock.advance(200)
    stats = s1.network_stats(1)
    assert stats.send_queue_len >= 0
    assert stats.ping_ms >= 0


def test_native_p2p_remote_and_spectator_sharing_address_get_separate_endpoints():
    """A remote player and a spectator at the same address must be backed by
    separate endpoints, like the Python builder (builder.py:280-296) — a
    merged endpoint would mark the remote player's endpoint as spectator and
    never send it local inputs."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    b = (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_clock(clock)
        .with_rng(random.Random(3))
        .with_native_sessions(True)
        .add_player(PlayerType.local(), 0)
        .add_player(PlayerType.remote("b"), 1)
        .add_player(PlayerType.spectator("b"), 2)
    )
    s = b.start_p2p_session(net.socket("a"))
    assert len(s._addr_of_ep) == 2
    assert s._remote_ep_of_addr["b"] != s._spec_ep_of_addr["b"]
    assert s._eps_of_addr["b"] == [0, 1]


# ---------------------------------------------------------------------------
# Spectator
# ---------------------------------------------------------------------------


def build_host_and_spectator(clock, net, *, native=(True, True),
                             catchup_speed=1, max_frames_behind=10):
    hb = (
        SessionBuilder(input_size=1)
        .with_num_players(1)
        .with_clock(clock)
        .with_rng(random.Random(21))
        .add_player(PlayerType.local(), 0)
        .add_player(PlayerType.spectator("spec"), 1)
    )
    if native[0]:
        hb = hb.with_native_sessions(True)
    host = hb.start_p2p_session(net.socket("host"))
    sb = (
        SessionBuilder(input_size=1)
        .with_num_players(1)
        .with_clock(clock)
        .with_rng(random.Random(22))
        .with_max_frames_behind(max_frames_behind)
        .with_catchup_speed(catchup_speed)
    )
    if native[1]:
        sb = sb.with_native_sessions(True)
    spec = sb.start_spectator_session("host", net.socket("spec"))
    return host, spec


def sync_host_spec(host, spec, clock):
    for _ in range(60):
        host.poll_remote_clients()
        spec.poll_remote_clients()
        host.events()
        spec.events()
        clock.advance(20)
        if (
            host.current_state() == SessionState.RUNNING
            and spec.current_state() == SessionState.RUNNING
        ):
            return
    raise AssertionError("host/spectator failed to synchronize")


def test_native_spectator_large_catchup_burst():
    """catchup_speed larger than the default request buffer must not drop
    requests (regression: SERR_CAPACITY after the native advance had
    already moved spec_current_frame silently skipped frames)."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host, spec = build_host_and_spectator(
        clock, net, native=(True, True), catchup_speed=40, max_frames_behind=50
    )
    sync_host_spec(host, spec, clock)

    g_host, g_spec = GameStub(), GameStub()
    # host races 55 frames ahead while the spectator sits idle
    for frame in range(55):
        host.add_local_input(0, bytes([frame % 9]))
        g_host.handle_requests(host.advance_frame())
        clock.advance(16)
    spec.poll_remote_clients()
    assert spec.frames_behind_host() > 50
    # one catch-up advance yields catchup_speed requests, none lost
    requests = spec.advance_frame()
    assert len(requests) == 40
    g_spec.handle_requests(requests)
    assert spec.current_frame == 39
    for f, v in g_spec.history.items():
        assert g_host.history[f] == v


@pytest.mark.parametrize("native", [(True, True), (True, False), (False, True)])
def test_native_spectator_follows_host(native):
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host, spec = build_host_and_spectator(clock, net, native=native)
    sync_host_spec(host, spec, clock)

    g_host, g_spec = GameStub(), GameStub()
    from ggrs_tpu import PredictionThreshold

    for frame in range(30):
        host.add_local_input(0, bytes([frame % 9]))
        g_host.handle_requests(host.advance_frame())
        try:
            g_spec.handle_requests(spec.advance_frame())
        except PredictionThreshold:
            pass  # host input not here yet; wait
        clock.advance(16)

    # settle: spectator catches up on everything confirmed
    for _ in range(40):
        host.poll_remote_clients()
        try:
            g_spec.handle_requests(spec.advance_frame())
        except PredictionThreshold:
            break
        clock.advance(16)

    assert g_spec.history, "spectator never advanced"
    for f, v in g_spec.history.items():
        assert g_host.history[f] == v, f"spectator diverged at frame {f}"
