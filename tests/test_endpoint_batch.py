"""Vectorized protocol plane parity (network/endpoint_batch.py).

The fleet pass replaces the per-peer Python timer/event/send scan with
one array program per pump; these tests pin that the replacement is
BIT-IDENTICAL to the scalar twin it rides above:

  1. view parity: adopting an endpoint swaps its hot-state backing for
     a fleet-row view and retiring swaps it back, with every field
     surviving bit-exact and live mutation visible through both;
  2. mesh parity: seeded lossy/reordering/duplicating 2-player meshes
     driven forced-fleet vs forced-scalar vs legacy pin identical wire
     bytes per socket IN SEND ORDER, identical endpoint state,
     identical NetworkStats and bitwise checksum histories — Python
     and native endpoints;
  3. lifecycle parity: adopt -> retire -> re-adopt mid-run changes
     nothing observable;
  4. crossover: a fleet-of-one pass stays on the scalar twin (no
     adoption), matching pump.py's SMALL_BATCH routing story;
  5. hosted parity: a SessionHost fleet above the crossover takes the
     vectorized plane (nonzero fleet passes) and stays bitwise equal,
     device state included, to the scalar-twin and legacy-pump hosts.
"""

import random

import numpy as np
import pytest

from ggrs_tpu import DesyncDetection, PlayerType, SessionBuilder, SessionState
from ggrs_tpu.errors import GGRSError
from ggrs_tpu.native import available
from ggrs_tpu.network.endpoint_batch import EndpointFleet, _FleetRow
from ggrs_tpu.network.messages import encode_message
from ggrs_tpu.network.protocol import (
    _HOT_BOOL_FIELDS,
    _HOT_INT_FIELDS,
    PeerEndpoint,
    _ScalarHot,
)
from ggrs_tpu.network.pump import GLOBAL_PUMP
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.utils.clock import FakeClock

BIG = 1 << 30  # a small_fleet no pass ever reaches: pins the scalar twin


class WireTap:
    """Socket wrapper recording every datagram shipped, in send order —
    the bitwise witness that two pump configurations put IDENTICAL bytes
    on the wire in IDENTICAL order."""

    def __init__(self, sock):
        self._sock = sock
        self.sent = []

    def send_to(self, msg, addr):
        self.sent.append((encode_message(msg), addr))
        self._sock.send_to(msg, addr)

    def send_wire(self, wire, addr):
        self.sent.append((bytes(wire), addr))
        self._sock.send_wire(wire, addr)

    def send_wire_batch(self, batch):
        for wire, addr in batch:
            self.sent.append((bytes(wire), addr))
        self._sock.send_wire_batch(batch)

    def receive_all_wire(self):
        return self._sock.receive_all_wire()

    def receive_all_messages(self):
        return self._sock.receive_all_messages()


def endpoint_state(ep):
    """Observable endpoint state, hot fields included (works through
    either backing store)."""
    state = {
        "state": ep.state,
        "remote_magic": ep.remote_magic,
        "packets_recv": ep.packets_recv,
        "bytes_recv": ep.bytes_recv,
        "packets_sent": ep.packets_sent,
        "bytes_sent": ep.bytes_sent,
        "pending": list(ep.pending_output),
        "recv_inputs": dict(ep.recv_inputs),
        "recv_frame": ep.recv_frame,
        "connect": [(s.disconnected, s.last_frame)
                    for s in ep.peer_connect_status],
        "checksums": dict(ep.checksum_history),
        "events": list(ep.event_queue),
        "sends": [encode_message(m) for m in ep.send_queue],
    }
    for name in _HOT_INT_FIELDS + _HOT_BOOL_FIELDS:
        state[name] = getattr(ep, name)
    return state


def network_stats_or_none(ep):
    try:
        return ep.network_stats()
    except GGRSError:
        return None


def make_endpoint(seed, clock):
    return PeerEndpoint(
        handles=[1], peer_addr="peer", num_players=2, local_players=1,
        max_prediction=8, disconnect_timeout_ms=2000,
        disconnect_notify_start_ms=500, fps=60, input_size=1,
        clock=clock, rng=random.Random(seed),
    )


# ----------------------------------------------------------------------
# 1. row-view adopt/retire roundtrip
# ----------------------------------------------------------------------


class _SoloProfile:
    """Minimal fleet-adoptable stand-in for a session: one endpoint."""

    def __init__(self, ep):
        self.ep = ep
        self.emitted = []
        self._fleet_state = None

    def _fleet_profile(self):
        return {
            "endpoints": [self.ep],
            "emits": [self.emitted.append],
            "adv_n": 0,
            "connect_status": [],
            "checksums": False,
        }


def test_adopt_retire_roundtrip_is_bit_exact():
    clock = FakeClock()
    clock.advance(1234)
    ep = make_endpoint(3, clock)
    ep.synchronize()  # non-trivial hot state: magic, timers, queued sync
    before = endpoint_state(ep)
    assert isinstance(ep._hot, _ScalarHot)

    fleet = EndpointFleet(cap=2)
    holder = _SoloProfile(ep)
    assert fleet.adopt(holder)
    assert isinstance(ep._hot, _FleetRow)
    assert fleet.live_rows == 1 and fleet.live_sessions == 1
    assert endpoint_state(ep) == before  # the view changes nothing

    # mutation through the view lands in the columns and reads back as
    # plain Python scalars
    row = holder._fleet_state.start
    ep.round_trip_time = 42
    assert fleet.cols["round_trip_time"][row] == 42
    assert ep.round_trip_time == 42 and type(ep.round_trip_time) is int
    ep.disconnect_notify_sent = True
    assert bool(fleet.cols["disconnect_notify_sent"][row]) is True
    ep.round_trip_time = before["round_trip_time"]
    ep.disconnect_notify_sent = before["disconnect_notify_sent"]

    # queue appends while adopted set the dirty flags
    assert not fleet.cols["events_dirty"][row]
    ep.event_queue.append("ev")
    assert fleet.cols["events_dirty"][row]
    ep.event_queue.clear()

    fleet.retire_session(holder)
    assert isinstance(ep._hot, _ScalarHot)
    assert holder._fleet_state is None
    assert fleet.live_rows == 0 and fleet.free_blocks == [(0, 1)]
    assert endpoint_state(ep) == before

    # adopting again reuses the freed block and growth keeps views live
    assert fleet.adopt(holder)
    assert holder._fleet_state.start == 0
    others = [_SoloProfile(make_endpoint(9 + i, clock)) for i in range(4)]
    for o in others:
        assert fleet.adopt(o)  # forces _grow past cap=2
    assert fleet.cap >= 5
    ep.round_trip_time = 77  # view must still hit the (rebound) columns
    assert fleet.cols["round_trip_time"][holder._fleet_state.start] == 77


def test_native_sessions_are_unfleetable():
    if not available():
        pytest.skip("native library not built")
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=0, jitter_ms=0, loss=0.0, seed=1)
    s = (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_clock(clock)
        .with_native_endpoints(True)
        .add_player(PlayerType.local(), 0)
        .add_player(PlayerType.remote("b"), 1)
        .start_p2p_session(net.socket("a"))
    )
    assert s._fleet_profile() is None
    assert not EndpointFleet(cap=2).adopt(s)


# ----------------------------------------------------------------------
# 2./3. mesh parity: fleet vs scalar vs legacy on hostile wire
# ----------------------------------------------------------------------


def drive_mesh(mode, use_native, ticks=120, loss=0.05, duplicate=0.08,
               seed=11, lifecycle=False):
    """2-player mesh over a seeded lossy/reordering/duplicating wire.

    mode: "fleet" pins the vectorized plane (crossover forced to 0),
    "scalar" pins the scalar twin (crossover unreachable), "legacy"
    pins the per-message pump end-to-end. All nondeterminism is seeded
    and all clocks virtual, so any cross-mode difference is a real
    behavioral divergence. `lifecycle=True` additionally retires and
    re-adopts mid-run (fleet mode only) — it must change nothing."""
    from stubs import GameStub

    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=15, jitter_ms=6, loss=loss,
                          duplicate=duplicate, seed=seed)

    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(8)
            .with_clock(clock)
            .with_desync_detection_mode(DesyncDetection.on(interval=10))
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        if use_native:
            b = b.with_native_endpoints(True)
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        return b.start_p2p_session(WireTap(net.socket(my_addr)))

    sessions = [build("a", "b", 0), build("b", "a", 1)]
    games = [GameStub(), GameStub()]
    saved = GLOBAL_PUMP.small_fleet
    if mode == "fleet":
        GLOBAL_PUMP.small_fleet = 0
    elif mode == "scalar":
        GLOBAL_PUMP.small_fleet = BIG
    elif mode == "legacy":
        for s in sessions:
            s.batched_pump = False
    # any other mode keeps the real SMALL_FLEET crossover
    try:
        for _ in range(400):
            for s in sessions:
                s.poll_remote_clients()
                s.events()
            clock.advance(20)
            if all(
                s.current_state() == SessionState.RUNNING for s in sessions
            ):
                break
        else:
            raise AssertionError("mesh failed to synchronize")

        script = random.Random(seed ^ 0xBEEF)
        inputs = [
            [script.randrange(16) for _ in range(ticks)] for _ in range(2)
        ]
        for t in range(ticks):
            if lifecycle and t == ticks // 3:
                # retire mid-run: endpoints drop back to scalar hot
                # state; the next pump pass re-adopts them
                for s in sessions:
                    if s._fleet_state is not None:
                        s._fleet_state.fleet.retire_session(s)
            for i, s in enumerate(sessions):
                s.add_local_input(i, bytes([inputs[i][t]]))
                games[i].handle_requests(s.advance_frame())
                s.events()
            clock.advance(16)

        adopted = sum(s._fleet_state is not None for s in sessions)
        report = []
        for s, g in zip(sessions, games):
            remotes = list(s.player_reg.remotes.values())
            report.append({
                "frame": s.current_frame,
                "checksum_history": dict(s.local_checksum_history),
                "connect": [(c.disconnected, c.last_frame)
                            for c in s.local_connect_status],
                "game_state": (g.gs.frame, g.gs.state),
                "wire": list(s.socket.sent),
                "endpoints": [
                    endpoint_state(ep) if not use_native else None
                    for ep in remotes
                ],
                "stats": [network_stats_or_none(ep) for ep in remotes],
            })
        return report, adopted
    finally:
        GLOBAL_PUMP.small_fleet = saved
        for s in sessions:
            if s._fleet_state is not None:
                s._fleet_state.fleet.retire_session(s)


@pytest.mark.parametrize(
    "use_native", [False] + ([True] if available() else [])
)
def test_mesh_parity_fleet_vs_scalar_vs_legacy(use_native):
    fleet, fleet_adopted = drive_mesh("fleet", use_native)
    scalar, scalar_adopted = drive_mesh("scalar", use_native)
    legacy, _ = drive_mesh("legacy", use_native)
    assert fleet == scalar
    assert scalar_adopted == 0
    if use_native:
        # native endpoints are unfleetable by design: the forced-fleet
        # run must have routed them to the scalar twin
        assert fleet_adopted == 0
    else:
        assert fleet_adopted == 2
        # wire bytes per socket in send order are the strongest pin;
        # make sure the run put real traffic AND stats on them
        assert all(len(r["wire"]) > 50 for r in fleet)
        assert all(st is not None for r in fleet for st in r["stats"])
        assert all(r["checksum_history"] for r in fleet)
    # the legacy per-message pump sends per-datagram instead of batched,
    # but the BYTES per socket in order must match exactly
    for fr, lr in zip(fleet, legacy):
        assert fr["wire"] == lr["wire"]
        assert fr["checksum_history"] == lr["checksum_history"]
        assert fr["frame"] == lr["frame"]
        assert fr["game_state"] == lr["game_state"]
        assert fr["connect"] == lr["connect"]
        assert fr["stats"] == lr["stats"]


def test_mesh_parity_survives_adopt_retire_cycles():
    cycled, _ = drive_mesh("fleet", False, lifecycle=True)
    scalar, _ = drive_mesh("scalar", False)
    assert cycled == scalar


def test_crossover_fleet_of_one_stays_scalar():
    """Below SMALL_FLEET the pump must keep the scalar twin: standalone
    small meshes never pay adoption or the fixed vectorized-pass cost."""
    assert GLOBAL_PUMP.small_fleet >= 2
    passes_before = GLOBAL_PUMP.fleet.passes
    report, adopted = drive_mesh("default", False, ticks=40)
    assert adopted == 0
    assert GLOBAL_PUMP.fleet.passes == passes_before
    assert report[0]["checksum_history"]


# ----------------------------------------------------------------------
# 5. hosted parity: vectorized vs scalar twin vs legacy pump
# ----------------------------------------------------------------------


def build_hosted_fleet(mode, seed=13):
    from ggrs_tpu.models.ex_game import ExGame
    from ggrs_tpu.serve import SessionHost
    from ggrs_tpu.serve.loadgen import (
        build_matches,
        drive_scripted,
        make_scripts,
        sync_fleet,
    )

    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=20, jitter_ms=8, loss=0.03,
                          duplicate=0.02, seed=seed)
    host = SessionHost(
        ExGame(num_players=4, num_entities=16),
        max_prediction=8, num_players=4, max_sessions=12,
        clock=clock, idle_timeout_ms=0,
        batched_pump=(mode != "legacy"),
    )
    if mode == "scalar":
        host._pump.small_fleet = BIG
    matches = build_matches(host, net, clock, sessions=8, seed=seed)
    sync_fleet(host, matches, clock)
    ticks = 60
    scripts = make_scripts(matches, ticks, seed=seed)
    desyncs = drive_scripted(host, matches, clock, scripts, ticks)
    assert not desyncs, f"hosted fleet desynced (mode={mode})"
    host.device.block_until_ready()
    return host, matches


def test_hosted_fleet_vectorized_parity():
    import jax

    host_f, matches_f = build_hosted_fleet("fleet")
    host_s, matches_s = build_hosted_fleet("scalar")
    host_l, matches_l = build_hosted_fleet("legacy")
    # the default host is above the crossover: the vectorized plane ran
    assert host_f._pump.fleet.passes > 0
    assert host_f._pump.fleet.live_rows >= host_f._pump.small_fleet
    assert host_s._pump.fleet.passes == 0
    stats = host_f._host_section()["endpoint_fleet"]
    assert stats["vectorized_passes"] > 0 and stats["rows_live"] > 0

    keys = [
        [k for keys in m for k in keys]
        for m in (matches_f, matches_s, matches_l)
    ]
    assert len(keys[0]) == len(keys[1]) == len(keys[2]) >= 8
    for kf, ks, kl in zip(*keys):
        sf = host_f.session(kf)
        ss = host_s.session(ks)
        sl = host_l.session(kl)
        assert sf.current_frame == ss.current_frame == sl.current_frame
        assert (
            sf.local_checksum_history
            == ss.local_checksum_history
            == sl.local_checksum_history
        )
        for ref_host, ref_key in ((host_s, ks), (host_l, kl)):
            a = host_f.device.state_numpy(host_f._lanes[kf].slot)
            b = ref_host.device.state_numpy(ref_host._lanes[ref_key].slot)
            for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                assert np.array_equal(np.asarray(la), np.asarray(lb))

    # detach retires every fleet row; the fleet must drain to empty
    for k in list(keys[0]):
        host_f.detach(k)
    assert host_f._pump.fleet.live_rows == 0
    assert host_f._pump.fleet.live_sessions == 0
