"""Adversarial wire fuzzing: every byte a peer sends is untrusted input.

The reference's only packet defenses are bincode decode failures and the
magic filter; here we actively fuzz the decode surfaces — random garbage,
bit-flipped real packets, truncations — through BOTH stacks' endpoints and
the native session core. The invariants: no crash, no exception escaping
the endpoint, and honest sessions still converge afterwards. Run against
`make sanitize` (UBSAN) to also catch silent undefined behavior in the C++
decode paths.
"""

import random

import pytest

from ggrs_tpu import PlayerType, SessionBuilder, SessionState
from ggrs_tpu.native import available
from ggrs_tpu.network.compression import rle_decode
from ggrs_tpu.network.messages import DecodeError, decode_message
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.utils.clock import FakeClock
from stubs import GameStub

NATIVE_PARAMS = [False] + ([True] if available() else [])


def build_pair(clock, net, use_native):
    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        if use_native:
            b = b.with_native_sessions(True)
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        return b.start_p2p_session(net.socket(my_addr))

    return build("a", "b", 0), build("b", "a", 1)


def sync_pair(s0, s1, clock):
    for _ in range(400):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(20)
        if (
            s0.current_state() == SessionState.RUNNING
            and s1.current_state() == SessionState.RUNNING
        ):
            return
    raise AssertionError("failed to synchronize")


class FuzzingSocket:
    """Wraps an InMemorySocket, injecting hostile datagrams into receives
    and (optionally) mutating real ones at the byte level."""

    def __init__(self, inner, rng, peer_addr, mutate=True):
        self.inner = inner
        self.rng = rng
        self.peer_addr = peer_addr
        self.mutate = mutate

    def send_to(self, msg, addr):
        self.inner.send_to(msg, addr)

    def send_wire(self, wire, addr):
        self.inner.send_wire(wire, addr)

    def _hostile(self):
        kind = self.rng.randrange(3)
        if kind == 0:  # pure garbage
            n = self.rng.randrange(0, 64)
            return bytes(self.rng.randrange(256) for _ in range(n))
        if kind == 1:  # plausible header, garbage body
            body = bytes(self.rng.randrange(256) for _ in range(self.rng.randrange(40)))
            return bytes([self.rng.randrange(256), self.rng.randrange(256),
                          self.rng.randrange(9)]) + body
        # truncated/malformed RLE input message shape
        return b"\x00" * self.rng.randrange(1, 8)

    def receive_all_messages(self):
        out = list(self.inner.receive_all_messages())
        mutated = []
        for src, msg in out:
            if self.mutate and self.rng.random() < 0.2:
                from ggrs_tpu.network.messages import encode_message

                wire = bytearray(encode_message(msg))
                for _ in range(self.rng.randrange(1, 4)):
                    wire[self.rng.randrange(len(wire))] ^= 1 << self.rng.randrange(8)
                try:
                    mutated.append((src, decode_message(bytes(wire))))
                except DecodeError:
                    continue  # undecodable mutation = dropped datagram
            else:
                mutated.append((src, msg))
        # inject hostile packets claiming to come from the real peer
        for _ in range(self.rng.randrange(3)):
            try:
                mutated.append((self.peer_addr, decode_message(self._hostile())))
            except DecodeError:
                continue
        return mutated


def _attach_fuzzer(s0, rng, mutate):
    s0.socket = FuzzingSocket(s0.socket, rng, "b", mutate=mutate)
    if hasattr(s0, "_wire_recv"):
        s0._wire_recv = hasattr(s0.socket, "receive_all_wire")
        s0._wire_send = hasattr(s0.socket, "send_wire")
    else:
        s0._wire_dispatch = None  # Python session re-probes the socket


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
@pytest.mark.parametrize("seed", [1, 7, 23])
def test_sessions_ignore_injected_garbage(use_native, seed):
    """Threat model 1: off-stream garbage (random bytes, plausible headers,
    truncations) from the peer's address. None of it carries the session
    magic, so the full correctness contract holds: progress AND identical
    confirmed prefixes."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=10, seed=seed)
    s0, s1 = build_pair(clock, net, use_native)
    sync_pair(s0, s1, clock)
    _attach_fuzzer(s0, random.Random(seed * 977), mutate=False)

    g0, g1 = GameStub(), GameStub()
    for frame in range(60):
        s0.add_local_input(0, bytes([frame % 9]))
        g0.handle_requests(s0.advance_frame())
        s1.add_local_input(1, bytes([(frame * 3) % 9]))
        g1.handle_requests(s1.advance_frame())
        s0.events()
        s1.events()
        clock.advance(16)
    for _ in range(10):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(16)
    s0.add_local_input(0, b"\x00")
    g0.handle_requests(s0.advance_frame())
    s1.add_local_input(1, b"\x00")
    g1.handle_requests(s1.advance_frame())

    confirmed = min(s0.confirmed_frame(), s1.confirmed_frame())
    assert confirmed > 20, f"garbage stalled the session (confirmed={confirmed})"
    for f in range(1, confirmed + 1):
        assert g0.history[f] == g1.history[f]


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
@pytest.mark.parametrize("seed", [1, 7, 23])
def test_sessions_survive_in_stream_tampering(use_native, seed):
    """Threat model 2: bit-flips on real packets that survive the magic
    filter. Like the reference, the wire has no MAC, so tampering CAN stall
    the stream (forged acks desync the delta reference) or corrupt inputs
    (divergence). The contract under fire: every packet is absorbed as an
    orderly, catchable condition — never a crash/assert — and any replica
    divergence is caught by desync detection."""
    from ggrs_tpu import DesyncDetected, DesyncDetection
    from ggrs_tpu.errors import GGRSError

    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=10, seed=seed)

    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
            .with_desync_detection_mode(DesyncDetection.on(8))
        )
        if use_native:
            b = b.with_native_sessions(True)
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        return b.start_p2p_session(net.socket(my_addr))

    s0, s1 = build("a", "b", 0), build("b", "a", 1)
    sync_pair(s0, s1, clock)
    _attach_fuzzer(s0, random.Random(seed * 977), mutate=True)

    g0, g1 = GameStub(), GameStub()
    events = []
    for frame in range(120):
        for s, g, handle, mult in ((s0, g0, 0, 1), (s1, g1, 1, 3)):
            try:
                s.add_local_input(handle, bytes([(frame * mult) % 9]))
                g.handle_requests(s.advance_frame())
            except GGRSError:
                pass  # stalled stream: skip the frame, like a real client
        events += s0.events() + s1.events()
        clock.advance(16)

    confirmed = min(s0.confirmed_frame(), s1.confirmed_frame())
    assert confirmed > 3, f"no progress at all (confirmed={confirmed})"
    upto = min(confirmed, max(g0.history, default=0), max(g1.history, default=0))
    diverged = any(g0.history[f] != g1.history[f] for f in range(1, upto + 1))
    if diverged:
        assert any(isinstance(e, DesyncDetected) for e in events), (
            "tampering diverged the replicas without a DesyncDetected event"
        )


@pytest.mark.parametrize("seed", range(20))
def test_rle_decoder_parity_on_garbage(seed):
    """Both RLE decoders (Python oracle + native) must never crash on
    arbitrary bytes — and must AGREE: same decoded bytes, or both reject.
    A decoder accepting what the other rejects would desync a native peer
    from a Python peer on the same wire."""
    rng = random.Random(seed)
    blob = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 200)))
    try:
        py_result = ("ok", rle_decode(blob))
    except ValueError:
        py_result = ("error", None)
    if available():
        from ggrs_tpu.native import rle_decode as native_rle_decode

        try:
            nat_result = ("ok", native_rle_decode(blob))
        except ValueError:
            nat_result = ("error", None)
        assert py_result == nat_result, f"decoder outcomes diverged on seed {seed}"


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
def test_spoofed_pre_sync_start_frame_cannot_poison_session(use_native):
    """Regression: before synchronization the magic filter accepts any
    packet, and an InputMsg with a huge start_frame used to poison
    recv_inputs (last_recv jumps to ~2e9, every real input thereafter is
    'already received' and dropped; its ack also popped the peer's whole
    pending window). The endpoint must drop it and the session must run
    normally afterwards."""
    from ggrs_tpu.network.compression import rle_encode
    from ggrs_tpu.network.messages import InputMsg, Message, encode_message

    clock = FakeClock()
    net = InMemoryNetwork(clock, seed=2)
    s0, s1 = build_pair(clock, net, use_native)

    # one zero-delta frame for a 1-byte single-handle input stream
    poison = Message(
        magic=0x4141,
        body=InputMsg(
            peer_connect_status=[],
            disconnect_requested=False,
            start_frame=2_000_000_000,
            ack_frame=-1,
            bytes_=rle_encode(b"\x00"),
        ),
    )
    attacker = net.socket("b")  # spoofing the real peer's address
    for _ in range(3):
        attacker.send_wire(encode_message(poison), "a")
    s0.poll_remote_clients()

    sync_pair(s0, s1, clock)
    g0, g1 = GameStub(), GameStub()
    for frame in range(30):
        s0.add_local_input(0, bytes([frame % 9]))
        g0.handle_requests(s0.advance_frame())
        s1.add_local_input(1, bytes([(frame * 3) % 9]))
        g1.handle_requests(s1.advance_frame())
        clock.advance(16)
    confirmed = min(s0.confirmed_frame(), s1.confirmed_frame())
    assert confirmed > 15, f"poisoned session stalled (confirmed={confirmed})"
    for f in range(1, confirmed + 1):
        assert g0.history[f] == g1.history[f]


@pytest.mark.parametrize("kind", ["python"] + (["native"] if available() else []))
def test_negative_start_frame_post_sync_is_dropped(kind):
    """An in-stream InputMsg with start_frame = INT32_MIN carrying the real
    peer's magic (a bit-flipped genuine packet) must be dropped after sync:
    in the C++ endpoint `start_frame - 1` would be signed overflow — UB
    under `make sanitize`. Driven at the endpoint level so the filter sees
    the authentic magic deterministically."""
    from ggrs_tpu.frame_info import PlayerInput
    from ggrs_tpu.network.compression import rle_encode
    from ggrs_tpu.network.messages import InputMsg, Message, encode_message
    from ggrs_tpu.sync_layer import ConnectionStatus
    from test_native_endpoint import make_pair, pump

    clock = FakeClock()
    net = InMemoryNetwork(clock)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair(kind, kind, clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=12)
    assert ep_a.is_running() and ep_b.is_running()

    # a few real frames so last_recv advances past NULL_FRAME
    for f in range(3):
        ep_b.send_input({0: PlayerInput(f, bytes([f]))}, status)
        ep_b.send_all_messages(sock_b)
        pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=1)

    poison = Message(
        magic=ep_b.magic,  # authentic sender magic: passes the filter
        body=InputMsg(
            peer_connect_status=[ConnectionStatus(), ConnectionStatus()],
            disconnect_requested=False,
            start_frame=-(1 << 31),
            ack_frame=-1,
            bytes_=rle_encode(b"\x00"),
        ),
    )
    wire = encode_message(poison)
    if hasattr(ep_a, "handle_wire"):
        ep_a.handle_wire(wire)
    else:
        from ggrs_tpu.network.messages import decode_message

        ep_a.handle_message(decode_message(wire))

    # the stream continues normally: frames 3.. arrive and are sequential
    got = []
    for f in range(3, 8):
        ep_b.send_input({0: PlayerInput(f, bytes([f]))}, status)
        ep_b.send_all_messages(sock_b)
        events = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=1)
        got += [e.input.frame for e in events[id(ep_a)] if hasattr(e, "input")]
    assert got == list(range(3, 8)), f"input stream broken after poison: {got}"


class SyncReplyBlackhole:
    """Drops SyncReply datagrams toward the wrapped socket until `until_ms`
    on the shared clock — forcing the asymmetric handshake state where the
    peer is already RUNNING while this side still waits for its final
    roundtrip."""

    MSG_SYNC_REPLY = 1  # wire byte 2 (messages.py body tags)

    def __init__(self, inner, clock, until_ms):
        self.inner = inner
        self.clock = clock
        self.until_ms = until_ms

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _keep(self, wire):
        if self.clock.now_ms() >= self.until_ms:
            return True
        return len(wire) < 3 or wire[2] != self.MSG_SYNC_REPLY

    def receive_all_wire(self):
        return [(a, w) for a, w in self.inner.receive_all_wire() if self._keep(w)]

    def receive_all_messages(self):
        from ggrs_tpu.network.messages import decode_all

        return decode_all(self.receive_all_wire())


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
def test_asymmetric_handshake_recovers_despite_quality_chatter(use_native):
    """Regression (livelock inherited from the reference, protocol.rs:353):
    when one peer completes the handshake and the other loses the final
    SyncReply, the running peer's 200ms quality reports made the stuck
    side's QualityReplies refresh last_send_time forever, starving its
    sync-request retries. Retries now key off the last sync request: once
    the blackhole lifts, the pair must synchronize."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    s0, s1 = build_pair(clock, net, use_native)
    # s1 loses every SyncReply until t=1200ms (past phase 1's 800ms, lifted
    # mid-chatter in phase 2)
    s1.socket = SyncReplyBlackhole(s1.socket, clock, until_ms=1200)
    if hasattr(s1, "_wire_recv"):
        s1._wire_recv = True
    else:
        s1._wire_dispatch = None

    # s0 completes and starts ticking (quality reports flow); s1 is stuck
    for _ in range(40):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(20)
    assert s0.current_state() == SessionState.RUNNING
    assert s1.current_state() == SessionState.SYNCHRONIZING
    from ggrs_tpu.errors import PredictionThreshold

    g0 = GameStub()
    for frame in range(60):  # sustained quality-report chatter toward s1
        try:
            s0.add_local_input(0, b"\x01")
            g0.handle_requests(s0.advance_frame())
        except PredictionThreshold:
            s0.poll_remote_clients()  # window full: wait on the stuck peer
        s1.poll_remote_clients()
        clock.advance(16)  # passes the 1200ms mark mid-loop
    for _ in range(40):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(20)
    assert s1.current_state() == SessionState.RUNNING, (
        "handshake retries starved by quality-reply chatter"
    )


@pytest.mark.parametrize("seed", range(10))
def test_native_endpoint_handles_arbitrary_bytes(seed):
    """Raw bytes straight into the C++ endpoint state machine (no Python
    codec filter in front): must return, never abort."""
    if not available():
        pytest.skip("native library not built")
    from ggrs_tpu.native.endpoint import NativePeerEndpoint
    from ggrs_tpu.utils.clock import FakeClock

    ep = NativePeerEndpoint(
        handles=[1], peer_addr="x", num_players=2, local_players=1,
        max_prediction=8, disconnect_timeout_ms=2000,
        disconnect_notify_start_ms=500, fps=60, input_size=1,
        clock=FakeClock(), rng=random.Random(seed),
    )
    ep.synchronize()
    rng = random.Random(seed * 31)
    for _ in range(400):
        n = rng.randrange(0, 80)
        ep.handle_wire(bytes(rng.randrange(256) for _ in range(n)))
    ep.poll([])  # state machine still functional
