"""Device rollback backend: request-stream fusion must be semantically
identical to fulfilling the same requests one-by-one on host (the oracle
path), including through rollbacks, ring reuse and checksum production."""

import numpy as np
import pytest

from ggrs_tpu import AdvanceFrame, LoadGameState, SaveGameState, SessionBuilder
from ggrs_tpu.models import ex_game
from ggrs_tpu.ops.fixed_point import combine_checksum

NUM_PLAYERS = 2
ENTITIES = 128


class OracleRunner:
    """Fulfills the ordered request list on host with the numpy oracle —
    the straight, unfused execution of the same contract."""

    def __init__(self):
        self.state = ex_game.init_oracle(NUM_PLAYERS, ENTITIES)

    def _copy(self):
        return {k: np.copy(v) for k, v in self.state.items()}

    def handle_requests(self, requests):
        for req in requests:
            if isinstance(req, SaveGameState):
                assert int(self.state["frame"]) == req.frame
                req.cell.save(
                    req.frame,
                    self._copy(),
                    combine_checksum(*ex_game.checksum_oracle(self.state)),
                )
            elif isinstance(req, LoadGameState):
                data = req.cell.load()
                assert data is not None
                self.state = {k: np.copy(v) for k, v in data.items()}
            elif isinstance(req, AdvanceFrame):
                inputs = np.array([buf[0] for buf, _ in req.inputs], dtype=np.uint8)
                statuses = np.array([int(s) for _, s in req.inputs], dtype=np.int32)
                self.state = ex_game.step_oracle(
                    self.state, inputs, statuses, NUM_PLAYERS
                )


def drive_synctest(handler, frames, check_distance, max_prediction=8, seed=3):
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(NUM_PLAYERS)
        .with_max_prediction_window(max_prediction)
        .with_check_distance(check_distance)
        .start_synctest_session()
    )
    rng = np.random.default_rng(seed)
    for frame in range(frames):
        for h in range(NUM_PLAYERS):
            sess.add_local_input(h, bytes([int(rng.integers(0, 16))]))
        handler.handle_requests(sess.advance_frame())


@pytest.mark.parametrize("check_distance", [2, 7])
def test_fused_backend_matches_oracle(check_distance):
    from ggrs_tpu.tpu import TpuRollbackBackend

    game = ex_game.ExGame(NUM_PLAYERS, ENTITIES)
    backend = TpuRollbackBackend(game, max_prediction=8, num_players=NUM_PLAYERS)
    oracle = OracleRunner()

    drive_synctest(backend, 60, check_distance)
    drive_synctest(oracle, 60, check_distance)

    dev = backend.state_numpy()
    for key in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(np.asarray(dev[key]), oracle.state[key])


def test_synctest_checksum_consistency_on_device():
    """The fused device path must survive SyncTest's per-tick forced rollback
    + checksum-history comparison for a long run (no MismatchedChecksum)."""
    from ggrs_tpu.tpu import TpuRollbackBackend

    game = ex_game.ExGame(NUM_PLAYERS, ENTITIES)
    backend = TpuRollbackBackend(game, max_prediction=8, num_players=NUM_PLAYERS)
    drive_synctest(backend, 300, check_distance=4)
    assert backend.current_frame == 300


def test_snapshot_refs_and_lazy_checksums():
    from ggrs_tpu.tpu import SnapshotRef, TpuRollbackBackend

    game = ex_game.ExGame(NUM_PLAYERS, ENTITIES)
    backend = TpuRollbackBackend(game, max_prediction=4, num_players=NUM_PLAYERS)

    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(NUM_PLAYERS)
        .with_max_prediction_window(4)
        .with_check_distance(2)
        .start_synctest_session()
    )
    cells = []
    for frame in range(6):
        for h in range(NUM_PLAYERS):
            sess.add_local_input(h, bytes([frame]))
        reqs = sess.advance_frame()
        backend.handle_requests(reqs)
        cells += [r.cell for r in reqs if isinstance(r, SaveGameState)]

    # cells hold device snapshot handles + resolvable checksums
    assert all(isinstance(c.load(), SnapshotRef) for c in cells)
    assert all(isinstance(c.checksum, int) for c in cells)


def test_multi_segment_request_list():
    """Sparse-saving P2P ticks can contain two Load-led rollback blocks in
    one request list; the backend must fuse each segment separately."""
    from ggrs_tpu.sync_layer import GameStateCell
    from ggrs_tpu.tpu import TpuRollbackBackend

    game = ex_game.ExGame(NUM_PLAYERS, 64)
    backend = TpuRollbackBackend(game, max_prediction=4, num_players=NUM_PLAYERS)

    def adv(frame):
        return AdvanceFrame(
            inputs=[(bytes([frame % 7]), 0), (bytes([(frame * 3) % 7]), 0)]
        )

    c0, c1 = GameStateCell(), GameStateCell()
    backend.handle_requests(
        [SaveGameState(c0, 0), adv(0), SaveGameState(c1, 1), adv(1)]
    )
    assert backend.current_frame == 2

    c1b, c0b = GameStateCell(), GameStateCell()
    backend.handle_requests(
        [
            LoadGameState(c0, 0), adv(0), SaveGameState(c1b, 1), adv(1),
            LoadGameState(c0, 0), adv(0), adv(1),
        ]
    )
    assert backend.current_frame == 2
    # both segments replayed the same inputs from the same snapshot: the
    # final state must equal the straight-line oracle
    oracle = ex_game.init_oracle(NUM_PLAYERS, 64)
    for f in range(2):
        inputs = np.array([f % 7, (f * 3) % 7], dtype=np.uint8)
        oracle = ex_game.step_oracle(oracle, inputs, np.zeros(2, np.int32), NUM_PLAYERS)
    dev = backend.state_numpy()
    for key in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(np.asarray(dev[key]), oracle[key])


def test_deferred_synctest_on_device_matches_oracle():
    """Deferred checksum verification over the device backend: same end
    state as the oracle, no mismatch, and the ledger batches transfers
    (each drain burst resolves every pending checksum batch at once)."""
    from ggrs_tpu.tpu import TpuRollbackBackend

    game = ex_game.ExGame(NUM_PLAYERS, ENTITIES)
    backend = TpuRollbackBackend(game, max_prediction=8, num_players=NUM_PLAYERS)
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(NUM_PLAYERS)
        .with_max_prediction_window(8)
        .with_check_distance(4)
        .with_deferred_checksum_verification(10)
        .start_synctest_session()
    )
    rng = np.random.default_rng(3)
    for frame in range(80):
        for h in range(NUM_PLAYERS):
            sess.add_local_input(h, bytes([int(rng.integers(0, 16))]))
        backend.handle_requests(sess.advance_frame())
    sess.flush_checksum_checks()
    # every batch an observation referenced is resolved without a fresh
    # round trip: drains prefetch the next burst's batches, so resolution
    # consumes landed host copies. Only batches no observation ever read
    # (at most the last burst's tail, registered after the final in-run
    # prefetch) may remain unresolved in the ledger.
    unresolved = [b for b in backend.ledger._pending if b._np is None]
    assert len(unresolved) <= 2
    assert all(not b._prefetched for b in unresolved)

    oracle = OracleRunner()
    drive_synctest(oracle, 80, check_distance=4, seed=3)
    dev = backend.state_numpy()
    for key in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(np.asarray(dev[key]), oracle.state[key])


def test_checksum_ledger_batches_fetches(monkeypatch):
    """One resolve() call must fetch ALL pending batches in a single
    jax.device_get (the transfer-count contract the tunnel perf relies on)."""
    import jax

    from ggrs_tpu.tpu import TpuRollbackBackend

    game = ex_game.ExGame(NUM_PLAYERS, 64)
    backend = TpuRollbackBackend(game, max_prediction=4, num_players=NUM_PLAYERS)
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(NUM_PLAYERS)
        .with_max_prediction_window(4)
        .with_check_distance(2)
        .start_synctest_session()
    )
    cells = []
    for frame in range(8):
        for h in range(NUM_PLAYERS):
            sess.add_local_input(h, bytes([frame % 5]))
        reqs = sess.advance_frame()
        backend.handle_requests(reqs)
        cells += [r.cell for r in reqs if isinstance(r, SaveGameState)]
    # Reading ONE checksum must resolve every pending batch via a single
    # packed device->host transfer; the remaining reads must cost nothing.
    import ggrs_tpu.tpu.backend as backend_mod

    transfers = []
    orig_asarray = np.asarray

    def counting_asarray(x, *args, **kwargs):
        if isinstance(x, jax.Array):
            transfers.append(1)
        return orig_asarray(x, *args, **kwargs)

    monkeypatch.setattr(backend_mod.np, "asarray", counting_asarray)
    _ = [c.checksum for c in cells[-4:]]
    assert sum(transfers) == 1
    assert all(b._np is not None for b in backend.ledger._pending) or not backend.ledger._pending
