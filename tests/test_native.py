"""Native (C++) kernel parity vs the pure-Python format oracle.

Builds the shared library once per session (g++ is in the image); every
property is checked byte-for-byte against ggrs_tpu.network.compression and
ggrs_tpu.ops.fixed_point.
"""

import random

import numpy as np
import pytest

from ggrs_tpu.network import compression as pycomp
from ggrs_tpu.ops import fixed_point as fx


@pytest.fixture(scope="module")
def native():
    from ggrs_tpu import native as nat
    from ggrs_tpu.native.build import build

    if not nat.available():
        if not build():
            pytest.skip("no native toolchain")
        nat._load_attempted = False  # retry after the build
    if not nat.available():
        pytest.fail("native library built but failed to load")
    return nat


def _cases(rng, count=200):
    for _ in range(count):
        n = rng.randrange(0, 600)
        yield bytes(
            rng.choice([0, 0, 0, 0xFF, 0xFF, rng.randrange(256)]) for _ in range(n)
        )


def test_rle_encode_matches_python_exactly(native):
    rng = random.Random(1)
    for data in _cases(rng):
        assert native.rle_encode(data) == pycomp.rle_encode(data)


def test_rle_decode_roundtrip_and_cross(native):
    rng = random.Random(2)
    for data in _cases(rng):
        enc_native = native.rle_encode(data)
        # native decodes python's encoding and vice versa
        assert native.rle_decode(pycomp.rle_encode(data)) == data
        assert pycomp.rle_decode(enc_native) == data


def test_delta_matches_python(native):
    rng = random.Random(3)
    for _ in range(100):
        m = rng.randrange(1, 33)
        k = rng.randrange(1, 20)
        ref = bytes(rng.randrange(256) for _ in range(m))
        pending = [bytes(rng.randrange(256) for _ in range(m)) for _ in range(k)]
        assert native.delta_encode(ref, pending) == pycomp.delta_encode(ref, pending)
        data = pycomp.delta_encode(ref, pending)
        assert native.delta_decode(ref, data) == pycomp.delta_decode(ref, data)


def test_full_codec_cross_implementation(native):
    rng = random.Random(4)
    ref = bytes(rng.randrange(256) for _ in range(8))
    pending = [bytes(rng.randrange(256) for _ in range(8)) for _ in range(32)]
    # python-encoded stream decodes identically through the native path
    wire = pycomp.rle_encode(pycomp.delta_encode(ref, pending))
    assert native.delta_decode(ref, native.rle_decode(wire)) == pending


def test_malformed_rle_rejected(native):
    with pytest.raises(ValueError):
        native.rle_decode(b"\x83")  # truncated varint
    with pytest.raises(ValueError):
        native.rle_decode(b"\x0c\xaa")  # literal run longer than stream


def test_weighted_checksum_matches_python(native):
    rng = np.random.default_rng(5)
    for n in (0, 1, 7, 1024):
        words = rng.integers(0, 2**32, size=n, dtype=np.uint32)
        with np.errstate(over="ignore"):
            hi, lo = fx.weighted_checksum(words, np)
        nhi, nlo = native.weighted_checksum_bytes(words.tobytes())
        assert (int(hi), int(lo)) == (nhi, nlo)
