"""Randomized cross-feature soak: every round-3 device feature (lazy tick
batching, beam speculation with partial-prefix adoption, their
composition) must be bit-indistinguishable from the plain per-tick
backend under randomized input statistics — and a live P2P pair with the
features split across peers must keep the framework's own desync
detector silent. The r2 sharded-peer test is the model
(tests/test_sharded_backend.py); these are its feature-flag twins."""

import numpy as np
import pytest

from ggrs_tpu import (
    DesyncDetected,
    DesyncDetection,
    PlayerType,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.tpu import TpuRollbackBackend
from ggrs_tpu.utils.clock import FakeClock

PLAYERS = 2
ENTITIES = 64


def make_backend(**kw):
    return TpuRollbackBackend(
        ExGame(num_players=PLAYERS, num_entities=ENTITIES),
        max_prediction=6,
        num_players=PLAYERS,
        **kw,
    )


def hold_script(rng, ticks):
    """Randomized hold/toggle/novel-value inputs — the statistics that
    actually produce partial-prefix matches."""
    out = np.zeros((ticks, PLAYERS), dtype=np.uint8)
    for p in range(PLAYERS):
        f = 0
        recent = [1 + p, 9 + p]
        while f < ticks:
            hold = int(rng.integers(1, 9))
            v = (
                int(rng.integers(0, 16))
                if rng.random() < 0.3
                else recent[int(rng.integers(0, 2))]
            )
            recent = [recent[-1], v]
            out[f : f + hold, p] = v
            f += hold
    return out


@pytest.mark.parametrize(
    "kw",
    [
        {"lazy_ticks": 5},
        {"beam_width": 16},
        {"lazy_ticks": 3, "beam_width": 16},
        # the adaptive gate's width decisions (full / width-1 history /
        # none, value-attributed by member) under the same random
        # streams: every choice must stay bit-identical to plain resim
        {"beam_width": 8, "speculation_gate": "adaptive"},
    ],
    ids=["lazy", "beam", "lazy+beam", "beam-adaptive"],
)
@pytest.mark.parametrize("seed", [1, 2])
def test_feature_synctest_soak_bit_parity(kw, seed):
    """Randomized SyncTest streams (forced rollbacks every tick) through a
    featured and a plain backend: final state and every saved checksum
    bit-identical, and with the beam on, speculation must actually serve
    frames (not silently no-op its way to parity)."""
    rng = np.random.default_rng(seed)
    script = hold_script(rng, 40)

    def make_sess():
        return (
            SessionBuilder(input_size=1)
            .with_num_players(PLAYERS)
            .with_max_prediction_window(6)
            .with_check_distance(4)
            .start_synctest_session()
        )

    featured, plain = make_backend(**kw), make_backend()
    if kw.get("speculation_gate") == "adaptive":
        # pretend-measured costs: the VALUE conditions (not the budget)
        # drive the width choices under this soak's timing-free loop
        featured._spec_cost_s = 1e-9
        featured._spec_hist_cost_s = 1e-9
    sf, sp = make_sess(), make_sess()
    # capture (frame, checksum_getter) AT SAVE TIME: ring cells are reused
    # every max_prediction+2 frames, so late cell reads would only compare
    # the final handful of saves — the getter is stable across overwrites
    f_saves, p_saves = [], []
    for t in range(40):
        for h in range(PLAYERS):
            sf.add_local_input(h, bytes([int(script[t, h])]))
            sp.add_local_input(h, bytes([int(script[t, h])]))
        rf, rp = sf.advance_frame(), sp.advance_frame()
        featured.handle_requests(rf)
        plain.handle_requests(rp)
        f_saves += [
            (r.cell.frame, r.cell.checksum_getter())
            for r in rf
            if hasattr(r, "cell")
        ]
        p_saves += [
            (r.cell.frame, r.cell.checksum_getter())
            for r in rp
            if hasattr(r, "cell")
        ]
    a, b = featured.state_numpy(), plain.state_numpy()
    for k in a:
        np.testing.assert_array_equal(
            np.asarray(a[k]), np.asarray(b[k]), err_msg=f"state[{k}] ({kw})"
        )
    assert len(f_saves) == len(p_saves)
    for (ff, fget), (pf, pget) in zip(f_saves, p_saves):
        assert ff == pf
        assert fget() == pget(), f"frame {ff} ({kw})"
    if kw.get("beam_width"):
        assert featured.rollback_frames_adopted > 0, kw


@pytest.mark.parametrize("seed,loss,jitter", [(2, 0.05, 40), (7, 0.15, 40)])
def test_lossy_net_feature_peers_no_desync(seed, loss, jitter):
    """The adversarial-network variant: latency + jitter + loss +
    duplication on the seeded fault-injecting net, feature-loaded peer
    (lazy batching + beam) vs plain peer, desync detection on. The
    protocol's ack/resend machinery must deliver every confirmed input
    and the detector must stay silent through the chaos."""
    from ggrs_tpu.errors import PredictionThreshold

    clock = FakeClock()
    net = InMemoryNetwork(clock=clock, latency_ms=30, jitter_ms=jitter,
                          loss=loss, duplicate=0.05, seed=seed)

    def build(my, other, h):
        import random

        return (
            SessionBuilder(input_size=1)
            .with_num_players(PLAYERS)
            .with_max_prediction_window(8)
            .with_desync_detection_mode(DesyncDetection.on(interval=10))
            .with_clock(clock)
            .with_rng(random.Random(seed * 100 + h))
            .add_player(PlayerType.local(), h)
            .add_player(PlayerType.remote(other), 1 - h)
            .start_p2p_session(net.socket(my))
        )

    sa, sb = build("a", "b", 0), build("b", "a", 1)
    ba = TpuRollbackBackend(
        ExGame(PLAYERS, ENTITIES), max_prediction=8, num_players=PLAYERS,
        lazy_ticks=3, beam_width=8,
    )
    bb = TpuRollbackBackend(
        ExGame(PLAYERS, ENTITIES), max_prediction=8, num_players=PLAYERS
    )
    for _ in range(600):
        sa.poll_remote_clients()
        sb.poll_remote_clients()
        sa.events()
        sb.events()
        clock.advance(20)
        if (
            sa.current_state() == SessionState.RUNNING
            and sb.current_state() == SessionState.RUNNING
        ):
            break
    assert sa.current_state() == SessionState.RUNNING, "handshake failed"

    rng = np.random.default_rng(seed)
    script = hold_script(rng, 90)
    desyncs, done = [], [0, 0]
    guard = 0
    while min(done) < 80 and guard < 4000:
        guard += 1
        for sess, backend, h in ((sa, ba, 0), (sb, bb, 1)):
            sess.poll_remote_clients()
            desyncs += [e for e in sess.events() if isinstance(e, DesyncDetected)]
            if done[h] < 80 and done[h] - min(done) < 7:
                try:
                    sess.add_local_input(h, bytes([int(script[done[h], h])]))
                    backend.handle_requests(sess.advance_frame())
                    done[h] += 1
                except PredictionThreshold:
                    pass  # window exhausted under loss; catch up via polling
        clock.advance(17)
    assert min(done) >= 80, f"stalled at {done} (loss={loss})"
    assert desyncs == [], f"desync under loss={loss}: {desyncs[:2]}"


def test_live_p2p_lazy_and_beam_peers_no_desync():
    """Peer A: lazy tick batching + beam speculation; peer B: plain
    backend. Desync detection on over the deterministic in-memory net with
    randomized hold inputs: the framework's own detector must stay silent
    for the whole run, and the rings must bit-agree at the last mutually
    confirmed frame."""
    # the shared P2P harness from the round-2 sharded-peer test (this
    # file's model): same builder shape, same sync loop, one definition
    from test_sharded_backend import build_pair, sync_sessions

    clock = FakeClock()
    net = InMemoryNetwork(clock=clock)
    sess_a, sess_b = build_pair(clock, net)
    back_a = TpuRollbackBackend(
        ExGame(PLAYERS, ENTITIES), max_prediction=8, num_players=PLAYERS,
        lazy_ticks=4, beam_width=8,
    )
    back_b = TpuRollbackBackend(
        ExGame(PLAYERS, ENTITIES), max_prediction=8, num_players=PLAYERS
    )
    sync_sessions([sess_a, sess_b], clock)

    rng = np.random.default_rng(17)
    script = hold_script(rng, 70)
    desyncs = []
    for frame in range(60):
        for sess, backend, handle in ((sess_a, back_a, 0), (sess_b, back_b, 1)):
            sess.poll_remote_clients()
            desyncs += [e for e in sess.events() if isinstance(e, DesyncDetected)]
            sess.add_local_input(handle, bytes([int(script[frame, handle])]))
            backend.handle_requests(sess.advance_frame())
        clock.advance(17)
    for _ in range(10):
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        clock.advance(17)
    for frame in range(60, 62):
        for sess, backend, handle in ((sess_a, back_a, 0), (sess_b, back_b, 1)):
            sess.poll_remote_clients()
            desyncs += [e for e in sess.events() if isinstance(e, DesyncDetected)]
            sess.add_local_input(handle, bytes([int(script[frame, handle])]))
            backend.handle_requests(sess.advance_frame())
        clock.advance(17)

    assert desyncs == [], f"feature peers desynced: {desyncs[:3]}"
    c = min(sess_a.confirmed_frame(), sess_b.confirmed_frame())
    assert c > 62 - back_a.core.ring_len
    back_a.flush()
    snap_a = back_a.core.fetch_ring_slot(c % back_a.core.ring_len)
    snap_b = back_b.core.fetch_ring_slot(c % back_b.core.ring_len)
    assert int(np.asarray(snap_a["frame"])) == c
    for k in snap_a:
        np.testing.assert_array_equal(
            np.asarray(snap_a[k]), np.asarray(snap_b[k]), err_msg=k
        )
