"""Deterministic match replays (ggrs_tpu/utils/replay.py): a recording of
the confirmed input stream, observed at the request boundary of a LIVE
session full of rollbacks and mispredictions, must replay from the
initial world to the exact bit state the live session reached — the
payoff of the determinism contract, and a feature the reference lacks
(its snapshots die with the process, SURVEY.md §5)."""

import random

import numpy as np
import pytest

from ggrs_tpu import PlayerType, SessionBuilder, SessionState
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.models.swarm import Swarm
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.tpu import TpuRollbackBackend
from ggrs_tpu.utils.clock import FakeClock
from ggrs_tpu.utils.replay import InputRecorder, load_replay, replay_to_state

PLAYERS = 2
ENTITIES = 64


def test_synctest_recording_replays_bitexact(tmp_path):
    """SyncTest session (forced rollbacks every tick): record at the
    request boundary, replay from scratch, compare final states."""
    game = ExGame(PLAYERS, ENTITIES)
    backend = TpuRollbackBackend(game, max_prediction=6, num_players=PLAYERS)
    recorder = InputRecorder()
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(6)
        .with_check_distance(4)
        .start_synctest_session()
    )
    rng = np.random.default_rng(31)
    for t in range(40):
        for h in range(PLAYERS):
            sess.add_local_input(h, bytes([int(rng.integers(0, 16))]))
        reqs = sess.advance_frame()
        recorder.observe(reqs)
        backend.handle_requests(reqs)
    recorder.confirm_through(backend.current_frame - 1)

    path = str(tmp_path / "match.npz")
    recorder.save(path, game)
    inputs, statuses = load_replay(path, ExGame(PLAYERS, ENTITIES))
    assert inputs.shape[0] == backend.current_frame

    final = replay_to_state(ExGame(PLAYERS, ENTITIES), inputs, statuses)
    live = backend.state_numpy()
    for k in live:
        np.testing.assert_array_equal(
            np.asarray(final[k]), np.asarray(live[k]), err_msg=k
        )


def test_live_p2p_recording_replays_bitexact():
    """The decisive case: a live P2P run full of mispredicted rollbacks
    (toggling held inputs at lag). Record on peer A; the replay must
    reproduce the ring snapshot of the last mutually confirmed frame."""
    clock = FakeClock()
    net = InMemoryNetwork(clock=clock)

    def build(my_addr, other_addr, handle):
        return (
            SessionBuilder(input_size=1)
            .with_num_players(PLAYERS)
            .with_max_prediction_window(8)
            .with_clock(clock)
            .with_rng(random.Random(99 + handle))
            .add_player(PlayerType.local(), handle)
            .add_player(PlayerType.remote(other_addr), 1 - handle)
            .start_p2p_session(net.socket(my_addr))
        )

    sess_a, sess_b = build("a", "b", 0), build("b", "a", 1)
    game = ExGame(PLAYERS, ENTITIES)
    back_a = TpuRollbackBackend(game, max_prediction=8, num_players=PLAYERS)
    back_b = TpuRollbackBackend(game, max_prediction=8, num_players=PLAYERS)
    recorder = InputRecorder()
    for _ in range(400):
        for s in (sess_a, sess_b):
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(
            s.current_state() == SessionState.RUNNING for s in (sess_a, sess_b)
        ):
            break
    assert sess_a.current_state() == SessionState.RUNNING

    for frame in range(50):
        for sess, backend, handle in ((sess_a, back_a, 0), (sess_b, back_b, 1)):
            sess.poll_remote_clients()
            sess.events()
            v = 3 if (frame // 5) % 2 == 0 else 11
            sess.add_local_input(handle, bytes([v + handle]))
            reqs = sess.advance_frame()
            if handle == 0:
                recorder.observe(reqs)
            backend.handle_requests(reqs)
        clock.advance(17)
    for _ in range(10):
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        clock.advance(17)
    for sess, backend, handle in ((sess_a, back_a, 0), (sess_b, back_b, 1)):
        sess.poll_remote_clients()
        sess.add_local_input(handle, b"\x01")
        reqs = sess.advance_frame()
        if handle == 0:
            recorder.observe(reqs)
        backend.handle_requests(reqs)

    c = min(sess_a.confirmed_frame(), sess_b.confirmed_frame())
    recorder.confirm_through(c - 1)
    inputs, statuses = recorder.confirmed_script()
    assert inputs.shape[0] >= c  # the confirmed prefix covers frames 0..c-1

    # replay frames 0..c-1: state after them == ring snapshot OF frame c
    final = replay_to_state(
        ExGame(PLAYERS, ENTITIES), inputs[:c], statuses[:c]
    )
    snap = back_a.core.fetch_ring_slot(c % back_a.core.ring_len)
    assert int(np.asarray(snap["frame"])) == c
    for k in snap:
        np.testing.assert_array_equal(
            np.asarray(final[k]), np.asarray(snap[k]), err_msg=k
        )


def test_replay_refuses_wrong_world(tmp_path):
    game = ExGame(PLAYERS, ENTITIES)
    backend = TpuRollbackBackend(game, max_prediction=6, num_players=PLAYERS)
    recorder = InputRecorder()
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(6)
        .with_check_distance(2)
        .start_synctest_session()
    )
    for t in range(6):
        for h in range(PLAYERS):
            sess.add_local_input(h, bytes([t % 7]))
        reqs = sess.advance_frame()
        recorder.observe(reqs)
        backend.handle_requests(reqs)
    recorder.confirm_through(backend.current_frame - 1)
    path = str(tmp_path / "m.npz")
    recorder.save(path, game)
    with pytest.raises(ValueError, match="recorded on"):
        load_replay(path, Swarm(PLAYERS, ENTITIES))
    with pytest.raises(ValueError, match="recorded on"):
        load_replay(path, ExGame(PLAYERS, ENTITIES * 2))
