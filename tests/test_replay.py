"""Deterministic match replays (ggrs_tpu/utils/replay.py): a recording of
the confirmed input stream, observed at the request boundary of a LIVE
session full of rollbacks and mispredictions, must replay from the
initial world to the exact bit state the live session reached — the
payoff of the determinism contract, and a feature the reference lacks
(its snapshots die with the process, SURVEY.md §5)."""

import random

import numpy as np
import pytest

from ggrs_tpu import PlayerType, SessionBuilder, SessionState
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.models.swarm import Swarm
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.tpu import TpuRollbackBackend
from ggrs_tpu.utils.clock import FakeClock
from ggrs_tpu.utils.replay import InputRecorder, load_replay, replay_to_state

PLAYERS = 2
ENTITIES = 64


def test_synctest_recording_replays_bitexact(tmp_path):
    """SyncTest session (forced rollbacks every tick): record at the
    request boundary, replay from scratch, compare final states."""
    game = ExGame(PLAYERS, ENTITIES)
    backend = TpuRollbackBackend(game, max_prediction=6, num_players=PLAYERS)
    recorder = InputRecorder()
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(6)
        .with_check_distance(4)
        .start_synctest_session()
    )
    rng = np.random.default_rng(31)
    for t in range(40):
        for h in range(PLAYERS):
            sess.add_local_input(h, bytes([int(rng.integers(0, 16))]))
        reqs = sess.advance_frame()
        recorder.observe(reqs)
        backend.handle_requests(reqs)
    recorder.confirm_through(backend.current_frame - 1)

    path = str(tmp_path / "match.npz")
    recorder.save(path, game)
    inputs, statuses = load_replay(path, ExGame(PLAYERS, ENTITIES))
    assert inputs.shape[0] == backend.current_frame

    final = replay_to_state(ExGame(PLAYERS, ENTITIES), inputs, statuses)
    live = backend.state_numpy()
    for k in live:
        np.testing.assert_array_equal(
            np.asarray(final[k]), np.asarray(live[k]), err_msg=k
        )


def test_live_p2p_recording_replays_bitexact():
    """The decisive case: a live P2P run full of mispredicted rollbacks
    (toggling held inputs at lag). Record on peer A; the replay must
    reproduce the ring snapshot of the last mutually confirmed frame."""
    clock = FakeClock()
    net = InMemoryNetwork(clock=clock)

    def build(my_addr, other_addr, handle):
        return (
            SessionBuilder(input_size=1)
            .with_num_players(PLAYERS)
            .with_max_prediction_window(8)
            .with_clock(clock)
            .with_rng(random.Random(99 + handle))
            .add_player(PlayerType.local(), handle)
            .add_player(PlayerType.remote(other_addr), 1 - handle)
            .start_p2p_session(net.socket(my_addr))
        )

    sess_a, sess_b = build("a", "b", 0), build("b", "a", 1)
    game = ExGame(PLAYERS, ENTITIES)
    back_a = TpuRollbackBackend(game, max_prediction=8, num_players=PLAYERS)
    back_b = TpuRollbackBackend(game, max_prediction=8, num_players=PLAYERS)
    recorder = InputRecorder()
    for _ in range(400):
        for s in (sess_a, sess_b):
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(
            s.current_state() == SessionState.RUNNING for s in (sess_a, sess_b)
        ):
            break
    assert sess_a.current_state() == SessionState.RUNNING

    for frame in range(50):
        for sess, backend, handle in ((sess_a, back_a, 0), (sess_b, back_b, 1)):
            sess.poll_remote_clients()
            sess.events()
            v = 3 if (frame // 5) % 2 == 0 else 11
            sess.add_local_input(handle, bytes([v + handle]))
            reqs = sess.advance_frame()
            if handle == 0:
                recorder.observe(reqs)
            backend.handle_requests(reqs)
        clock.advance(17)
    for _ in range(10):
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        clock.advance(17)
    for sess, backend, handle in ((sess_a, back_a, 0), (sess_b, back_b, 1)):
        sess.poll_remote_clients()
        sess.add_local_input(handle, b"\x01")
        reqs = sess.advance_frame()
        if handle == 0:
            recorder.observe(reqs)
        backend.handle_requests(reqs)

    c = min(sess_a.confirmed_frame(), sess_b.confirmed_frame())
    recorder.confirm_through(c - 1)
    inputs, statuses = recorder.confirmed_script()
    assert inputs.shape[0] >= c  # the confirmed prefix covers frames 0..c-1

    # replay frames 0..c-1: state after them == ring snapshot OF frame c
    final = replay_to_state(
        ExGame(PLAYERS, ENTITIES), inputs[:c], statuses[:c]
    )
    snap = back_a.core.fetch_ring_slot(c % back_a.core.ring_len)
    assert int(np.asarray(snap["frame"])) == c
    for k in snap:
        np.testing.assert_array_equal(
            np.asarray(final[k]), np.asarray(snap[k]), err_msg=k
        )


def test_replay_refuses_wrong_world(tmp_path):
    game = ExGame(PLAYERS, ENTITIES)
    backend = TpuRollbackBackend(game, max_prediction=6, num_players=PLAYERS)
    recorder = InputRecorder()
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(6)
        .with_check_distance(2)
        .start_synctest_session()
    )
    for t in range(6):
        for h in range(PLAYERS):
            sess.add_local_input(h, bytes([t % 7]))
        reqs = sess.advance_frame()
        recorder.observe(reqs)
        backend.handle_requests(reqs)
    recorder.confirm_through(backend.current_frame - 1)
    path = str(tmp_path / "m.npz")
    recorder.save(path, game)
    with pytest.raises(ValueError, match="recorded on"):
        load_replay(path, Swarm(PLAYERS, ENTITIES))
    with pytest.raises(ValueError, match="recorded on"):
        load_replay(path, ExGame(PLAYERS, ENTITIES * 2))


def _record_synctest(frames=60, seed=9):
    """A recorded SyncTest run; returns (game, inputs, statuses,
    replay-ground-truth per-frame checksums via a second live pass)."""
    game = ExGame(PLAYERS, ENTITIES)
    backend = TpuRollbackBackend(game, max_prediction=6, num_players=PLAYERS)
    recorder = InputRecorder()
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(6)
        .with_check_distance(4)
        .start_synctest_session()
    )
    rng = np.random.default_rng(seed)
    for _ in range(frames):
        for h in range(PLAYERS):
            sess.add_local_input(h, bytes([int(rng.integers(0, 16))]))
        reqs = sess.advance_frame()
        recorder.observe(reqs)
        backend.handle_requests(reqs)
    recorder.confirm_through(backend.current_frame - 1)
    inputs, statuses = recorder.confirmed_script()
    return game, inputs, statuses


def test_replay_seek_from_checkpoint(tmp_path):
    """Seeking: replay the first half, persist a seek point, replay the
    tail from it — final state bit-equal to the full-replay result, and a
    wrong-world seek point is refused."""
    from ggrs_tpu.utils.replay import (
        load_seek_checkpoint,
        save_seek_checkpoint,
    )

    game, inputs, statuses = _record_synctest()
    F = inputs.shape[0]
    mid = F // 2

    full = replay_to_state(game, inputs, statuses)
    half = replay_to_state(game, inputs[:mid], statuses[:mid])
    path = str(tmp_path / "seek.npz")
    save_seek_checkpoint(path, half, game)

    state, frame = load_seek_checkpoint(path, game)
    assert frame == mid
    tail = replay_to_state(
        game, inputs, statuses, start_state=state, start_frame=frame
    )
    for k in full:
        np.testing.assert_array_equal(
            np.asarray(full[k]), np.asarray(tail[k]), err_msg=k
        )

    with pytest.raises(ValueError, match="seek point was saved on"):
        load_seek_checkpoint(path, ExGame(PLAYERS, 128))
    # an offset that doesn't match the state's frame is refused too
    with pytest.raises(ValueError, match="seek state is frame"):
        replay_to_state(
            game, inputs, statuses, start_state=state, start_frame=mid + 1
        )


def test_desync_postmortem_pins_first_bad_frame(tmp_path):
    """The forensics verdict: against a peer history with one corrupted
    entry the postmortem reports exactly that frame and both checksums;
    against the intact history it reports agreement. Also exercises the
    seek-composed variant (postmortem of the tail only)."""
    from ggrs_tpu.utils.replay import (
        desync_postmortem,
        replay_checksums,
        save_seek_checkpoint,
        load_seek_checkpoint,
    )

    game, inputs, statuses = _record_synctest()
    F = inputs.shape[0]
    truth = replay_checksums(game, inputs, statuses)
    assert sorted(truth) == list(range(F))

    assert desync_postmortem(game, inputs, statuses, dict(truth)) is None

    bad = dict(truth)
    bad_frame = F - 12
    bad[bad_frame] ^= 0x5A5A
    # corrupt a LATER frame too: the verdict must be the FIRST one
    bad[F - 4] ^= 1
    verdict = desync_postmortem(game, inputs, statuses, bad)
    assert verdict is not None
    frame, ours, theirs = verdict
    assert frame == bad_frame
    assert ours == truth[bad_frame]
    assert theirs == bad[bad_frame]

    # seek-composed postmortem over the tail finds the same frame
    mid = F // 2
    half = replay_to_state(game, inputs[:mid], statuses[:mid])
    path = str(tmp_path / "seek.npz")
    save_seek_checkpoint(path, half, game)
    state, frame0 = load_seek_checkpoint(path, game)
    verdict2 = desync_postmortem(
        game, inputs, statuses, bad, start_state=state, start_frame=frame0
    )
    assert verdict2 is not None and verdict2[0] == bad_frame
