"""The integrated speculative beam: rollback-as-select inside
TpuRollbackBackend (the north star's 'InputQueue prediction fans out into a
beam of candidate input sequences evaluated in parallel on-device').

The plain (resimulating) backend is the oracle: driving the same
deterministic request streams through a beam backend must produce
bit-identical states and checksums, whether the beam hits (trajectory
adopted) or misses (fallback resim).
"""

import random

import numpy as np

from ggrs_tpu import PlayerType, SessionBuilder, SessionState
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.tpu import TpuRollbackBackend
from ggrs_tpu.utils.clock import FakeClock

ENTITIES = 64
PLAYERS = 2


def build_p2p_pair(max_prediction=6, seeds=(1234, 5678)):
    """Two P2P sessions over a deterministic in-memory net, synced to
    RUNNING. Fixed rng seeds: the protocol handshake must not depend on
    Python's per-process string-hash randomization."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)

    def build(my_addr, other_addr, local_handle, seed):
        return (
            SessionBuilder(input_size=1)
            .with_num_players(PLAYERS)
            .with_max_prediction_window(max_prediction)
            .with_clock(clock)
            .with_rng(random.Random(seed))
            .add_player(PlayerType.local(), local_handle)
            .add_player(PlayerType.remote(other_addr), 1 - local_handle)
            .start_p2p_session(net.socket(my_addr))
        )

    s0 = build("a", "b", 0, seeds[0])
    s1 = build("b", "a", 1, seeds[1])
    for _ in range(400):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(20)
        if (
            s0.current_state() == SessionState.RUNNING
            and s1.current_state() == SessionState.RUNNING
        ):
            break
    assert s0.current_state() == SessionState.RUNNING
    assert s1.current_state() == SessionState.RUNNING
    return clock, s0, s1


def make_backend(beam_width, max_prediction=6):
    return TpuRollbackBackend(
        ExGame(num_players=PLAYERS, num_entities=ENTITIES),
        max_prediction=max_prediction,
        num_players=PLAYERS,
        beam_width=beam_width,
    )


def make_synctest(check_distance=4, max_prediction=6):
    return (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(max_prediction)
        .with_check_distance(check_distance)
        .start_synctest_session()
    )


def assert_states_equal(a, b, context):
    sa, sb = a.state_numpy(), b.state_numpy()
    for k in sa:
        assert np.array_equal(np.asarray(sa[k]), np.asarray(sb[k])), (
            f"state[{k}] diverged {context}"
        )


def drive_synctest_pair(beam, plain, inputs_for, ticks):
    """Two identical sessions, one per backend; compare states every tick
    and saved checksums at the end."""
    sess_beam, sess_plain = make_synctest(), make_synctest()
    beam_cells, plain_cells = [], []
    for t in range(ticks):
        for h in range(PLAYERS):
            buf = inputs_for(t, h)
            sess_beam.add_local_input(h, buf)
            sess_plain.add_local_input(h, buf)
        rb = sess_beam.advance_frame()
        rp = sess_plain.advance_frame()
        beam.handle_requests(rb)
        plain.handle_requests(rp)
        beam_cells += [r.cell for r in rb if hasattr(r, "cell")]
        plain_cells += [r.cell for r in rp if hasattr(r, "cell")]
        assert_states_equal(beam, plain, f"at tick {t}")
    for cb, cp in zip(beam_cells, plain_cells):
        assert cb.frame == cp.frame
        assert cb.checksum == cp.checksum, f"checksum diverged at frame {cb.frame}"


def test_warmup_compiles_without_state_change():
    """warmup() (pre-session compile for real-time loops) must leave the
    game state and ring untouched, and ticks afterwards must match a
    backend that never warmed up."""
    warmed, fresh = make_backend(beam_width=4), make_backend(beam_width=4)
    before = warmed.state_numpy()
    warmed.warmup()
    after = warmed.state_numpy()
    for k in before:
        assert np.array_equal(np.asarray(before[k]), np.asarray(after[k]))
    drive_synctest_pair(warmed, fresh, lambda t, h: bytes([t % 5]), ticks=15)


def test_warmup_covers_every_tick_program():
    """warmup() must compile EVERY program a live loop can dispatch.
    Since T=1 row-content routing (ResimCore.tick_row), rollback
    rows run a different compiled program (_tick_branchless_fn) than
    trivial one-advance rows (_tick_fn) — a warmup that misses one leaves
    a multi-second compile stall inside the session (exactly the defect
    that inflated the r4 p2p4 bench 30x until its measurement loop called
    warmup()). Drive both row shapes plus the lazy multi-tick buffer
    after warmup and require that no new executable gets compiled."""
    backend = TpuRollbackBackend(
        ExGame(num_players=PLAYERS, num_entities=ENTITIES),
        max_prediction=6,
        num_players=PLAYERS,
        lazy_ticks=3,
    )
    backend.warmup()
    core = backend.core
    # the interactive world is small enough for the branchless program
    assert core._tick_branchless_fn is not None
    fns = {
        "tick_cond": core._tick_fn,
        "tick_branchless": core._tick_branchless_fn,
        "tick_multi": core._tick_multi_fn,
    }
    warmed = {name: fn._cache_size() for name, fn in fns.items()}
    for name, size in warmed.items():
        assert size >= 1, f"warmup() never compiled {name}"

    sess = make_synctest(check_distance=4, max_prediction=6)
    for t in range(12):
        for h in range(PLAYERS):
            sess.add_local_input(h, bytes([t % 5]))
        backend.handle_requests(sess.advance_frame())
    backend.flush()
    for name, fn in fns.items():
        assert fn._cache_size() == warmed[name], (
            f"{name} compiled a new executable after warmup() "
            f"({warmed[name]} -> {fn._cache_size()}): warmup no longer "
            "covers every dispatchable program"
        )


def test_beam_hits_on_steady_inputs_and_matches_resim():
    """Constant inputs: every forced SyncTest rollback's script equals the
    repeat-last beam member, so after the first speculation every tick is
    an adopted trajectory — and must be bit-identical to resimulation."""
    beam, plain = make_backend(beam_width=8), make_backend(beam_width=0)
    drive_synctest_pair(
        beam, plain, lambda t, h: bytes([3 + 2 * h]), ticks=25
    )
    # rollbacks begin once current_frame > check_distance; the very first
    # one misses (the anchor heuristic assumes a steady rollback depth, and
    # the depth jumps from 0 to check_distance there), every later one
    # must adopt
    assert beam.beam_hits >= 18 and beam.beam_misses <= 1, (
        beam.beam_hits, beam.beam_misses,
    )
    assert plain.beam_hits == 0


def test_beam_serves_known_history_on_varying_inputs_and_matches_resim():
    """Per-frame-varying inputs defeat every *prediction* — but a SyncTest
    rollback's script is PLAYED HISTORY, and known history is pinned into
    every member (beam.branching_beam base_rows/fixed): the known prefix
    is served from the precomputed trajectory and only the genuinely
    unknown newest frame resimulates, fused in the adopt dispatch. Before
    history pinning this exact stream was wall-to-wall misses; the pin
    turns it into the partial-adoption fast path — still bit-identical to
    plain resimulation (drive_synctest_pair asserts states every tick)."""
    beam, plain = make_backend(beam_width=8), make_backend(beam_width=0)
    drive_synctest_pair(
        beam, plain, lambda t, h: bytes([(t * (h + 3) + h) % 16]), ticks=25
    )
    rollbacks = beam.beam_hits + beam.beam_partial_hits + beam.beam_misses
    assert rollbacks >= 18, rollbacks
    # nearly every rollback adopts its known prefix (the first consulted
    # speculation may predate the ring snapshot it needs)
    adopted = beam.beam_hits + beam.beam_partial_hits
    assert adopted >= rollbacks - 2, (
        beam.beam_hits, beam.beam_partial_hits, beam.beam_misses,
    )
    # the adopted prefixes are real frames, not empty matches
    assert beam.rollback_frames_adopted >= 2 * rollbacks, (
        beam.rollback_frames_adopted, rollbacks,
    )
    assert plain.beam_hits == 0


def test_beam_perturbed_member_hits_in_p2p():
    """The P2P case the beam exists for: the blank first-frame prediction
    for the remote player is wrong, but the remote's real (constant) input
    matches a perturbed beam member, so the correcting rollback is adopted.
    Two identical session pairs (deterministic net) — the beam pair's
    backend states must track the plain pair's exactly."""

    # local constant 5, remote constant 2: the remote's value equals the
    # XOR-2 perturbation of the blank prediction, so member (pattern 2,
    # player 1) covers the corrected script
    results = []
    for beam_width in (8, 0):
        clock, s0, s1 = build_p2p_pair()
        backend0 = make_backend(beam_width)
        backend1 = make_backend(0)
        states = []
        for frame in range(20):
            s0.add_local_input(0, bytes([5]))
            backend0.handle_requests(s0.advance_frame())
            s1.add_local_input(1, bytes([2]))
            backend1.handle_requests(s1.advance_frame())
            states.append(backend0.state_numpy())
            clock.advance(16)
        results.append((backend0, states))

    beam_backend, beam_states = results[0]
    _plain_backend, plain_states = results[1]
    assert beam_backend.beam_hits >= 1, (
        beam_backend.beam_hits, beam_backend.beam_misses,
    )
    for t, (sa, sb) in enumerate(zip(beam_states, plain_states)):
        for k in sa:
            assert np.array_equal(np.asarray(sa[k]), np.asarray(sb[k])), (
                f"state[{k}] diverged at tick {t}"
            )


def test_branching_beam_hits_mid_window_toggle():
    """The press/release toggle with unknown timing: a player alternating
    between two held values switches mid-rollback-window. The branching
    candidate (switch to previous-distinct at that offset) must adopt where
    repeat-last alone cannot — and stay bit-identical to resimulation."""
    beam, plain = make_backend(beam_width=32), make_backend(beam_width=0)
    # hold 6 frames of value A, then 6 of value B, alternating; with
    # check_distance 4 the switch lands at every offset of the rollback
    # window over the run (both players toggle together: the correlated
    # all-switch/all-back families must carry this)
    script = lambda t, h: bytes([(5 if (t // 6) % 2 == 0 else 9) + h])
    drive_synctest_pair(beam, plain, script, ticks=40)
    # warmup misses aside, toggles at covered offsets must adopt: require a
    # majority of rollbacks adopted, not just one lucky hit
    assert beam.beam_hits > beam.beam_misses, (
        beam.beam_hits, beam.beam_misses,
    )


def test_branching_beam_generator_shapes():
    from ggrs_tpu.tpu.beam import branching_beam

    last = np.array([[5], [9]], dtype=np.uint8)
    prev = np.array([[5], [2]], dtype=np.uint8)  # player 1 toggles 2<->9
    beam = branching_beam(last, prev, window=6, beam_width=16)
    assert beam.shape == (16, 6, 2, 1)
    # member 0: pure repeat-last
    assert (beam[0, :, 0, 0] == 5).all() and (beam[0, :, 1, 0] == 9).all()
    # some member covers player 1 switching to 2 at offset 2 exactly
    want = np.full((6,), 9, dtype=np.uint8)
    want[2:] = 2
    assert any(
        np.array_equal(beam[b, :, 1, 0], want)
        and (beam[b, :, 0, 0] == 5).all()
        for b in range(16)
    )
    # player 0 has no history: whole-window XOR patterns, not offset splits
    assert any(
        (beam[b, :, 0, 0] == 5 ^ 1).all() and (beam[b, :, 1, 0] == 9).all()
        for b in range(16)
    )


def test_branching_beam_pins_known_history():
    from ggrs_tpu.tpu.beam import branching_beam

    # anchor sits 2 frames in the past: those rows were played. Player 0
    # is local (both cells ground truth); player 1's rows are unconfirmed
    # predictions (free to branch). The local player toggled 3->5 at the
    # newest played frame, so its tracked last (5) differs from the older
    # played row (3) — the exact shape that used to kill every member on
    # the played-prefix check.
    last = np.array([[5], [3]], dtype=np.uint8)
    prev = np.array([[3], [5]], dtype=np.uint8)
    base = np.array([[[3], [3]], [[5], [3]]], dtype=np.uint8)  # [S=2, P, I]
    fixed = np.array([[True, False], [True, False]])
    beam = branching_beam(
        last, prev, window=6, beam_width=16, base_rows=base, fixed=fixed
    )
    assert beam.shape == (16, 6, 2, 1)
    # EVERY member reproduces the fixed cells verbatim
    assert (beam[:, 0, 0, 0] == 3).all() and (beam[:, 1, 0, 0] == 5).all()
    # member 0 = played history + repeat-last future
    assert (beam[0, :2, 1, 0] == 3).all()
    assert (beam[0, 2:, 0, 0] == 5).all() and (beam[0, 2:, 1, 0] == 3).all()
    # some member covers the remote player's true value being 5 from the
    # newest played frame on (the toggle the prediction missed), while
    # keeping the local player's played+future rows intact — the member a
    # boundary rollback adopts
    assert any(
        (beam[b, 0, 1, 0] == 3)
        and (beam[b, 1:, 1, 0] == 5).all()
        and (beam[b, 0, 0, 0] == 3)
        and (beam[b, 1:, 0, 0] == 5).all()
        for b in range(16)
    ), beam[:, :, :, 0]
    # no two members are identical (duplicates are skipped at generation)
    keys = {beam[b].tobytes() for b in range(16)}
    assert len(keys) == 16


def test_branching_beam_invariants_fuzz():
    """Randomized generator invariants: any (last, prev, base, fixed,
    window, width) combination must (1) terminate, (2) reproduce every
    fixed cell verbatim in every member, (3) keep member 0 = pinned base
    + repeat-last future, and (4) emit no duplicate members except
    surplus copies of member 0 once the distinct pool is exhausted."""
    from ggrs_tpu.tpu.beam import branching_beam

    rng = np.random.default_rng(7)
    for _ in range(40):
        p = int(rng.integers(1, 5))
        i = int(rng.integers(1, 3))
        window = int(rng.integers(2, 12))
        width = int(rng.integers(1, 40))
        last = rng.integers(0, 256, size=(p, i)).astype(np.uint8)
        prev = rng.integers(0, 256, size=(p, i)).astype(np.uint8)
        if rng.random() < 0.5:
            S = int(rng.integers(0, window + 1))
            base = rng.integers(0, 256, size=(S, p, i)).astype(np.uint8)
            fixed = rng.random(size=(S, p)) < rng.random()
        else:
            S, base, fixed = 0, None, None
        beam = branching_beam(
            last, prev, window, width,
            max_offset=int(rng.integers(1, window + 1)),
            base_rows=base, fixed=fixed,
        )
        assert beam.shape == (width, window, p, i)
        if S:
            for pl in range(p):
                rows = np.nonzero(fixed[:, pl])[0]
                assert np.array_equal(
                    beam[:, rows, pl],
                    np.broadcast_to(base[rows, pl], (width,) + base[rows, pl].shape),
                ), "a member rewrote a fixed cell"
            assert np.array_equal(beam[0, :S], base)
        assert (beam[0, S:] == last[None]).all()
        keys = [beam[b].tobytes() for b in range(width)]
        member0 = keys[0]
        non_surplus = [k for k in keys[1:] if k != member0]
        assert len(non_surplus) == len(set(non_surplus)), (
            "duplicate non-member-0 candidates"
        )


def test_partial_prefix_adoption_core_parity():
    """core.adopt with matched < count: the served prefix comes from the
    trajectory, the suffix resimulates in the same dispatch — ring, live
    state and per-slot checksums must all be bit-identical to a plain
    fused resim of the corrected script."""
    from ggrs_tpu.tpu.resim import ResimCore

    game = ExGame(num_players=PLAYERS, num_entities=ENTITIES)
    rng = np.random.default_rng(42)
    W = 8  # max_prediction 6 -> window 8

    def fresh_core():
        core = ResimCore(game, max_prediction=6, num_players=PLAYERS)
        # run a few confirmed frames so the ring has real snapshots
        for f in range(4):
            inputs = np.zeros((W, PLAYERS, 1), dtype=np.uint8)
            inputs[0] = rng.integers(0, 16, size=(PLAYERS, 1))
            statuses = np.zeros((W, PLAYERS), dtype=np.int32)
            save_slots = np.full((W,), core.scratch_slot, dtype=np.int32)
            save_slots[0] = f % core.ring_len
            core.tick(False, 0, inputs, statuses, save_slots, 1, start_frame=f)
        return core

    rng_state = rng.bit_generator.state
    core_a = fresh_core()
    rng.bit_generator.state = rng_state
    core_b = fresh_core()

    # speculate 5 frames from the frame-3 snapshot on core_a
    B, L = 4, 5
    beam_inputs = rng.integers(0, 16, size=(B, L, PLAYERS, 1), dtype=np.uint8)
    beam_statuses = np.zeros((B, L, PLAYERS), dtype=np.int32)
    spec = core_a.speculate(3 % core_a.ring_len, beam_inputs, beam_statuses)

    # corrected script: member 2's rows for 3 frames, then a divergence
    count, matched, member = 5, 3, 2
    actual = np.zeros((W, PLAYERS, 1), dtype=np.uint8)
    actual[:count] = beam_inputs[member, :count]
    actual[matched:count] = (actual[matched:count] + 7) % 16  # suffix differs
    statuses = np.zeros((W, PLAYERS), dtype=np.int32)
    save_slots = np.full((W,), core_a.scratch_slot, dtype=np.int32)
    for i in range(count + 1):
        save_slots[i] = (3 + i) % core_a.ring_len

    core_a.adopt(
        spec, member, 3 % core_a.ring_len, save_slots, count,
        shift=0, load_frame=3, inputs=actual, statuses=statuses,
        matched=matched,
    )
    his_b, los_b = core_b.tick(
        True, 3 % core_b.ring_len, actual, statuses, save_slots, count,
        start_frame=3,
    )

    sa, sb = core_a.fetch_state(), core_b.fetch_state()
    for k in sa:
        assert np.array_equal(np.asarray(sa[k]), np.asarray(sb[k])), (
            f"live state[{k}] diverged"
        )
    for slot in range(core_a.ring_len):
        ra, rb = core_a.fetch_ring_slot(slot), core_b.fetch_ring_slot(slot)
        for k in ra:
            assert np.array_equal(np.asarray(ra[k]), np.asarray(rb[k])), (
                f"ring[{slot}][{k}] diverged"
            )


def test_full_adoption_branchless_core_parity():
    """Full hits route to the branchless pure-data-movement adopt program
    (ResimCore._adopt_full_impl): ring, live state and per-slot checksums
    must be bit-identical to BOTH a plain fused resim of the same script
    and the cond adopt program's results — at shift 0 and shift 1, with
    device_verify on (the verify carry masks the same way)."""
    from ggrs_tpu.tpu.resim import ResimCore

    game = ExGame(num_players=PLAYERS, num_entities=ENTITIES)
    W = 8
    played = np.random.default_rng(42).integers(
        0, 16, size=(4, PLAYERS, 1), dtype=np.uint8
    )

    def fresh_core():
        core = ResimCore(
            game, max_prediction=6, num_players=PLAYERS, device_verify=True
        )
        for f in range(4):
            inputs = np.zeros((W, PLAYERS, 1), dtype=np.uint8)
            inputs[0] = played[f]
            statuses = np.zeros((W, PLAYERS), dtype=np.int32)
            save_slots = np.full((W,), core.scratch_slot, dtype=np.int32)
            save_slots[0] = f % core.ring_len
            core.tick(False, 0, inputs, statuses, save_slots, 1, start_frame=f)
        return core

    for shift in (0, 1):
        anchor = 3 - shift
        rng = np.random.default_rng(7)
        B, L = 4, 6
        beam_inputs = rng.integers(
            0, 16, size=(B, L, PLAYERS, 1), dtype=np.uint8
        )
        # the adoption contract: the member's first `shift` rows must be
        # the inputs actually played between anchor and load
        beam_inputs[:, :shift] = played[anchor : anchor + shift]
        beam_statuses = np.zeros((B, L, PLAYERS), dtype=np.int32)
        count, member = 4, 2
        actual = np.zeros((W, PLAYERS, 1), dtype=np.uint8)
        actual[:count] = beam_inputs[member, shift : shift + count]
        statuses = np.zeros((W, PLAYERS), dtype=np.int32)
        save_slots = np.full((W,), 99, dtype=np.int32)

        results = {}
        for mode in ("branchless", "cond", "resim"):
            core = fresh_core()
            save_slots = np.full((W,), core.scratch_slot, dtype=np.int32)
            for i in range(count + 1):
                save_slots[i] = (3 + i) % core.ring_len
            if mode == "resim":
                his, los = core.tick(
                    True, 3 % core.ring_len, actual, statuses, save_slots,
                    count, start_frame=3,
                )
            else:
                if mode == "cond":
                    core._adopt_full_fn = None  # force the cond program
                else:
                    assert core._adopt_full_fn is not None
                spec = core.speculate(
                    anchor % core.ring_len, beam_inputs, beam_statuses
                )
                his, los = core.adopt(
                    spec, member, 3 % core.ring_len, save_slots, count,
                    shift=shift, load_frame=3, inputs=actual,
                    statuses=statuses,
                )
            results[mode] = (
                core.fetch_state(),
                [core.fetch_ring_slot(s) for s in range(core.ring_len)],
                np.asarray(his),
                np.asarray(los),
                core.check_device_verdict(),
            )

        ref = results["resim"]
        for mode in ("branchless", "cond"):
            got = results[mode]
            for k in ref[0]:
                assert np.array_equal(
                    np.asarray(got[0][k]), np.asarray(ref[0][k])
                ), f"live state[{k}] diverged ({mode}, shift={shift})"
            for slot in range(len(ref[1])):
                for k in ref[1][slot]:
                    assert np.array_equal(
                        np.asarray(got[1][slot][k]),
                        np.asarray(ref[1][slot][k]),
                    ), f"ring[{slot}][{k}] diverged ({mode}, shift={shift})"
            assert np.array_equal(got[2], ref[2]), (mode, shift, "his")
            assert np.array_equal(got[3], ref[3]), (mode, shift, "los")
            assert got[4] == ref[4], (mode, shift, "verify verdict")


def test_partial_prefix_adoption_in_synctest_pair():
    """Players toggling at DIFFERENT offsets inside the same rollback
    window: no single branching member covers both switches, so full
    adoption is impossible — the longest-prefix path must fire (serving
    frames up to the second switch) and stay bit-identical to resim."""
    beam, plain = make_backend(beam_width=32), make_backend(beam_width=0)

    def script(t, h):
        # player 0 toggles every 5 frames, player 1 every 7: switches
        # regularly land at different offsets of the 4-frame window
        period = 5 if h == 0 else 7
        return bytes([(3 if (t // period) % 2 == 0 else 12) + h])

    drive_synctest_pair(beam, plain, script, ticks=45)
    assert beam.beam_partial_hits > 0, (
        beam.beam_hits, beam.beam_partial_hits, beam.beam_misses,
    )
    # the headline metric: fraction of rollback frames served from
    # speculation — partial prefixes must contribute
    assert beam.rollback_frames_adopted > 0
    assert beam.rollback_frames >= beam.rollback_frames_adopted


def test_beam_requires_statuses_contract():
    """A game that hasn't declared the disconnect-only statuses contract
    must be rejected at construction (silent wrong adoption otherwise)."""
    import pytest

    class NoContractGame(ExGame):
        statuses_contract = None

    with pytest.raises(ValueError, match="statuses_contract"):
        TpuRollbackBackend(
            NoContractGame(num_players=PLAYERS, num_entities=ENTITIES),
            max_prediction=6,
            num_players=PLAYERS,
            beam_width=8,
        )
    # beam off: no contract needed (nothing is ever adopted)
    TpuRollbackBackend(
        NoContractGame(num_players=PLAYERS, num_entities=ENTITIES),
        max_prediction=6,
        num_players=PLAYERS,
        beam_width=0,
    )


def test_arena_beam_adoption_live_p2p():
    """The beam is game-agnostic: arena (declared statuses contract,
    cross-entity centroids) adopts in a live P2P session with sticky
    toggling inputs. (Bit-parity of adopted trajectories is covered by the
    synctest-pair tests above; adoption correctness for arena rests on the
    same enforced statuses contract.)"""
    from ggrs_tpu.models.arena import Arena

    clock, s0, s1 = build_p2p_pair()
    beam = TpuRollbackBackend(
        Arena(PLAYERS, 64), max_prediction=6, num_players=PLAYERS, beam_width=16
    )
    plain = TpuRollbackBackend(
        Arena(PLAYERS, 64), max_prediction=6, num_players=PLAYERS
    )
    for f in range(40):
        v = 1 if (f // 7) % 2 == 0 else 9  # sticky toggle
        s0.add_local_input(0, bytes([v]))
        beam.handle_requests(s0.advance_frame())
        s1.add_local_input(1, bytes([v ^ 3]))
        plain.handle_requests(s1.advance_frame())
        clock.advance(16)
    assert beam.beam_hits > 0, (beam.beam_hits, beam.beam_misses)


def test_value_gate_two_signals_and_probes():
    """The adaptive gate's VALUE conditions, one per launch width: no
    branch serves + no member-0 serves -> full stand-down with periodic
    full-width probe bursts (the pre-width behavior); no branch serves
    but member-0 serves (SyncTest-style replays) -> width-1 history-only
    launches between probes; branch serves -> full width, streak clears.
    The budget condition is per width: an idle budget too thin for the
    full rollout but thick enough for width-1 history launches gets
    them."""
    backend = TpuRollbackBackend(
        ExGame(PLAYERS, ENTITIES),
        max_prediction=6,
        num_players=PLAYERS,
        beam_width=4,
        speculation_gate="adaptive",
    )
    backend._spec_cost_s = 0.001
    backend._spec_hist_cost_s = 0.00025
    backend._idle_ema_s = 1.0  # budget condition comfortably satisfied
    interval, burst = backend.VALUE_PROBE_INTERVAL, backend.VALUE_PROBE_BURST

    # not enough samples yet: full width
    assert backend._launch_width() == 4

    # regime 1: nothing serves at all (P2P neutral statistics) — the
    # value-gated ticks stand fully down; probes burst at interval ends
    for _ in range(backend.VALUE_MIN_SAMPLES):
        backend._launch_value.append((0, 0, 4))
    decisions = [backend._launch_width() for _ in range(2 * interval)]
    assert decisions.count(4) == 2 * burst
    assert decisions.count(0) == 2 * (interval - burst)
    assert set(decisions[: interval - burst]) == {0}
    assert decisions[interval - burst : interval] == [4] * burst

    # regime 2: member 0 serves (forced-replay workload) but branches
    # don't — value-gated ticks drop to width-1 history launches instead
    # of standing down; probes still fire
    for _ in range(backend.VALUE_WINDOW):
        backend._launch_value.append((0, 3, 2))
    backend._value_gated_streak = 0
    decisions = [backend._launch_width() for _ in range(interval)]
    assert decisions.count(4) == burst
    assert decisions.count(1) == interval - burst
    assert set(decisions[: interval - burst]) == {1}

    # regime 3: branch members adopt again — full width, streak clears
    for _ in range(backend.VALUE_WINDOW):
        backend._launch_value.append((3, 0, 2))
    assert backend._launch_width() == 4
    assert backend._value_gated_streak == 0

    # regime 4 (blended): neither signal alone clears the bar but the
    # total does — width-1 would forfeit the branch share, so the gate
    # keeps the full width (the pre-split combined signal)
    backend._idle_ema_s = 1.0
    for _ in range(backend.VALUE_WINDOW):
        backend._launch_value.append((1, 1, 5))  # 0.2 + 0.2 per launch
    assert backend._launch_width() == 4
    assert backend._value_gated_streak == 0

    # budget: an oversubscribed loop that can't cover even the history
    # width launches nothing...
    backend._idle_ema_s = 0.0
    assert backend._launch_width() == 0
    # ...one that covers width-1 but not the full rollout harvests the
    # blended window's member-0 share at width 1 (its 0.2/launch clears
    # the idle-covered SOFT bar; the branch share is forfeited since the
    # full rollout doesn't fit the budget)
    backend._idle_ema_s = 0.0005
    assert backend._launch_width() == 1
    # ...but when member 0 serves NOTHING (pure branch value), width-1
    # is useless and the gate stands down despite the affordable cost
    for _ in range(backend.VALUE_WINDOW):
        backend._launch_value.append((1, 0, 5))
    assert backend._launch_width() == 0
    for _ in range(backend.VALUE_WINDOW):
        backend._launch_value.append((0, 3, 2))
    assert backend._launch_width() == 1

    # regime 5 (the soft bar's reason to exist): idle comfortably covers
    # the full cost and a RARE-rollback stream serves only 0.125
    # frames/launch — far under the hard bar, but real value at covered
    # cost, so the gate stays open instead of locking out the serves
    backend._idle_ema_s = 1.0
    for _ in range(backend.VALUE_WINDOW):
        backend._launch_value.append((1, 0, 8))
    assert backend._launch_width() == 4
    assert backend._value_gated_streak == 0


def test_value_gate_attribution_live():
    """Live attribution: on a varying-inputs stream (every launch misses
    or is superseded) the value window fills with zeros and the gate
    starts gating launches; states stay bit-identical to the plain
    backend throughout (gated ticks just resimulate)."""
    clock, s0, s1 = build_p2p_pair()
    beam = TpuRollbackBackend(
        ExGame(PLAYERS, ENTITIES),
        max_prediction=6,
        num_players=PLAYERS,
        beam_width=4,
        speculation_gate="adaptive",
    )
    beam._spec_cost_s = 1e-9  # pretend measured: budget never vetoes
    plain = TpuRollbackBackend(
        ExGame(PLAYERS, ENTITIES), max_prediction=6, num_players=PLAYERS
    )
    rng = np.random.default_rng(17)
    for f in range(70):
        a, b = int(rng.integers(0, 16)), int(rng.integers(0, 16))
        s0.add_local_input(0, bytes([a]))
        beam.handle_requests(s0.advance_frame())
        s1.add_local_input(1, bytes([b]))
        plain.handle_requests(s1.advance_frame())
        clock.advance(16)
    assert len(beam._launch_value) >= beam.VALUE_MIN_SAMPLES
    branch = sum(b for b, _, _ in beam._launch_value)
    hist = sum(h for _, h, _ in beam._launch_value)
    launches = sum(n for _, _, n in beam._launch_value)
    assert branch / launches < beam.MIN_SERVED_PER_LAUNCH
    # P2P rollbacks load at the FIRST INCORRECT frame, so member 0's
    # pinned (played) rows mismatch at offset 0 by construction: the
    # history signal must decay too, and value-gated ticks stand fully
    # down instead of paying for useless width-1 launches
    assert hist / launches < beam.MIN_SERVED_PER_LAUNCH
    assert beam.beam_gated > 0, "value gate never stood down"
    assert beam.beam_history_launches == 0, (
        "width-1 launches fired in a regime where member 0 cannot serve"
    )
    sa, sb = beam.state_numpy(), plain.state_numpy()
    for key in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(np.asarray(sa[key]), np.asarray(sb[key]))


def test_history_width_serves_forced_replays_live():
    """The width-1 history-only launch earning its keep: on a SyncTest
    stream with per-frame-varying inputs every adoption is a member-0
    (pinned-history) serve, so the adaptive gate drops the full width
    but KEEPS launching at width 1 — adoption throughput survives at
    1/B the rollout FLOPs, bit-identical to plain resimulation."""
    beam = TpuRollbackBackend(
        ExGame(PLAYERS, ENTITIES),
        max_prediction=6,
        num_players=PLAYERS,
        beam_width=8,
        speculation_gate="adaptive",
    )
    beam._spec_cost_s = 1e-9  # pretend measured: budget never vetoes
    beam._spec_hist_cost_s = 1e-9
    plain = make_backend(beam_width=0)
    drive_synctest_pair(
        beam, plain, lambda t, h: bytes([(t * (h + 3) + h) % 16]), ticks=60
    )
    assert beam.beam_gated > 0, "full width never dropped"
    assert beam.beam_history_launches > 0, (
        "history-only launches never fired in a member-0-serving regime"
    )
    # adoption kept working THROUGH the width drop: serves continued
    # after the first gated tick
    assert beam.beam_hits + beam.beam_partial_hits > beam.beam_misses
    hist = sum(h for _, h, _ in beam._launch_value)
    launches = sum(n for _, _, n in beam._launch_value)
    assert hist / launches >= beam.MIN_SERVED_PER_LAUNCH
