"""Device-domain fault tolerance: the deterministic fault seam
(serve/faults.py), slot quarantine with survivor bit-exactness, the
sampled SDC audit lane, the degradation ladder, and the fleet agent's
quarantine mini-failover.

The contract under test: one poisoned slot (or one failed dispatch, or
one wedged readback) costs exactly that slot — every surviving session
keeps ticking BIT-EXACTLY (state + ring bytes + checksum history) vs an
unfaulted twin, every quarantine surfaces as a typed SlotPoisoned with
a forensics bundle, and injected silent corruption is caught by the
audit lane within its sampling bound. Both serving arms (resident
mailbox loop and its dispatch-per-tick twin) and both layouts
(single-device and the 8-shard session mesh) are pinned.
"""

import numpy as np
import pytest

import jax

from ggrs_tpu.errors import (
    CheckpointIncompatible,
    DeviceDispatchFailed,
    InvalidRequest,
    InvariantViolation,
    MailboxLaneFull,
    SlotPoisoned,
)
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.obs import GLOBAL_TELEMETRY
from ggrs_tpu.serve import SessionHost
from ggrs_tpu.serve.faults import FAULT_KINDS, Fault, FaultInjector, FaultPlan
from ggrs_tpu.serve.loadgen import (
    FRAME_MS,
    build_matches,
    make_scripts,
    sync_fleet,
)
from ggrs_tpu.utils.clock import FakeClock

ENTITIES = 8


def _telemetry(tmp_path):
    GLOBAL_TELEMETRY.reset()
    GLOBAL_TELEMETRY.enabled = True
    GLOBAL_TELEMETRY.dump_dir = str(tmp_path)


def _telemetry_off():
    GLOBAL_TELEMETRY.enabled = False
    GLOBAL_TELEMETRY.dump_dir = None
    GLOBAL_TELEMETRY.reset()


def build_fleet(*, resident, sessions=16, ticks=60, seed=11, loss=0.0,
                plan=None, victims_matches=None, checkpoint_at=None,
                checkpoint_path=None, mesh=None, collect=None,
                **host_kw):
    """A seeded loadgen fleet with an optional FaultInjector. loss=0 by
    default: delivery is then deterministic regardless of rng draws, so
    a fault that changes the VICTIM match's traffic cannot perturb the
    survivors — the survivor-bitwise-parity arms rest on that."""
    clock = FakeClock()
    net = InMemoryNetwork(
        clock, latency_ms=20, jitter_ms=0, loss=loss, seed=seed
    )
    host = SessionHost(
        ExGame(num_players=4, num_entities=ENTITIES),
        max_prediction=8, num_players=4, max_sessions=sessions + 4,
        clock=clock, idle_timeout_ms=0, mesh=mesh,
        resident=resident, resident_ticks=8,
        max_inflight_rows=4 * (sessions + 4), **host_kw,
    )
    matches = build_matches(host, net, clock, sessions=sessions, seed=seed)
    sync_fleet(host, matches, clock)
    injector = None
    if plan is not None:
        victims = (
            [k for m in victims_matches for k in matches[m]]
            if victims_matches is not None
            else None
        )
        injector = FaultInjector(host, plan, victims=victims).install()
    scripts = make_scripts(matches, ticks, seed=seed)
    desyncs = []
    for t in range(ticks):
        if injector is not None:
            injector.advance(t)
        for m, keys in enumerate(matches):
            for k, key in enumerate(keys):
                if key in host._lanes:  # quarantined victims drop out
                    host.submit_input(key, k, bytes([scripts[(m, k)][t]]))
        for key, evs in host.tick().items():
            desyncs += [
                (key, e) for e in evs
                if type(e).__name__ == "DesyncDetected"
            ]
        if checkpoint_at is not None and t == checkpoint_at:
            host.checkpoint(checkpoint_path)
        if collect is not None:
            collect(t, host)
        clock.advance(FRAME_MS)
    audit_every = getattr(host, "_audit_every", 0)
    if audit_every:
        # audit cooldown: a fault injected on the run's last ticks must
        # still get its sampling bound's worth of audit passes (no
        # inputs are submitted, so no lane advances — read-only ticks)
        for _ in range(2 * audit_every + 2):
            host.tick()
            clock.advance(FRAME_MS)
    host.device.block_until_ready()
    host._resolve_audits(block=True)
    return host, matches, injector, desyncs


def survivor_desyncs(desyncs, host, matches, skip_matches):
    skip_keys = {
        k for m in skip_matches for k in matches[m]
    }
    return [(k, e) for k, e in desyncs if k not in skip_keys]


def assert_survivors_bitexact(host_f, host_t, matches, skip_matches):
    """Surviving sessions of the faulted arm vs the SAME keys on the
    unfaulted twin: frames, checksum histories, live world bytes AND
    ring bytes."""
    compared = 0
    for m, keys in enumerate(matches):
        if m in skip_matches:
            continue
        for key in keys:
            sf = host_f.session(key)
            st = host_t.session(key)
            assert sf.current_frame == st.current_frame > 0, (m, key)
            assert sf.local_checksum_history == st.local_checksum_history
            ex_f = host_f.device.export_slot(host_f._lanes[key].slot)
            ex_t = host_t.device.export_slot(host_t._lanes[key].slot)
            for part in ("state", "ring"):
                la = jax.tree_util.tree_leaves(ex_f[part])
                lb = jax.tree_util.tree_leaves(ex_t[part])
                for a, b in zip(la, lb):
                    np.testing.assert_array_equal(a, b)
            compared += 1
    assert compared > 0


# ----------------------------------------------------------------------
# the acceptance soak: every fault kind, survivors bit-exact, SDC
# caught, quarantines typed + forensics — resident and twin arms
# ----------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("resident", [True, False])
def test_fault_soak_every_kind_survivors_bitexact(tmp_path, resident):
    _telemetry(tmp_path)
    try:
        kinds = list(FAULT_KINDS)
        if not resident:
            # the mailbox seam does not exist on the dispatch-per-tick
            # arm: its storm kind is vacuous there
            kinds.remove("mailbox_storm")
        ticks = 70
        plan = FaultPlan(5, ticks, kinds=kinds, persist_dispatch=True)
        corrupt_ticks = [
            f.tick for f in plan.all_faults()
            if f.kind == "checkpoint_corrupt"
        ]
        ckpt = str(tmp_path / f"soak_{resident}.npz")
        host_f, matches, inj, desyncs = build_fleet(
            resident=resident, ticks=ticks, plan=plan,
            victims_matches=(0, 1),
            sdc_audit_every=2, checkpoint_at=corrupt_ticks[0],
            checkpoint_path=ckpt,
        )
        host_t, matches_t, _, desyncs_t = build_fleet(
            resident=resident, ticks=ticks,
            sdc_audit_every=2,
            checkpoint_at=corrupt_ticks[0],
            checkpoint_path=str(tmp_path / f"twin_{resident}.npz"),
        )
        # every armed kind actually fired
        for kind in kinds:
            assert inj.fired[kind] >= 1, (kind, inj.fired)
        # the injected SDC was caught by the audit lane and every
        # quarantine surfaced typed, with a forensics bundle
        poisoned = host_f.take_quarantines()
        assert poisoned, "no quarantines surfaced"
        reasons = {p.reason for p in poisoned}
        assert "sdc_audit" in reasons, reasons
        flipped_keys = {b["key"] for b in inj.bitflips}
        assert flipped_keys & {p.key for p in poisoned}
        for p in poisoned:
            assert isinstance(p, SlotPoisoned)
            assert p.forensics is not None
        assert host_f.audit_mismatches >= 1
        # the corrupted checkpoint is DETECTED, typed — never a shape
        # error or a silently-wrong restore
        assert inj.corrupted_checkpoints == [ckpt]
        from ggrs_tpu.utils.checkpoint import load_device_checkpoint

        with pytest.raises(CheckpointIncompatible):
            load_device_checkpoint(ckpt)
        # zero desyncs among survivors, and the survivors are BIT-EXACT
        # (state + ring + checksum history) vs the unfaulted twin
        assert not survivor_desyncs(desyncs, host_f, matches, {0, 1})
        assert not desyncs_t
        assert_survivors_bitexact(host_f, host_t, matches, {0, 1})
        # the fault counters flowed through both exporters
        prom = GLOBAL_TELEMETRY.prometheus()
        snap = host_f.telemetry()
        for name in (
            "ggrs_slot_quarantines_total",
            "ggrs_sdc_audits_total",
            "ggrs_sdc_mismatches_total",
            "ggrs_faults_injected_total",
        ):
            assert name in prom
            assert name in snap["metrics"]
        assert snap["host"]["quarantines"] == len(poisoned)
    finally:
        _telemetry_off()


@pytest.mark.slow
def test_fault_soak_sharded_resident(tmp_path):
    """The sharded acceptance arm: the same every-kind soak on an
    8-shard session mesh resident host, survivors bit-exact vs a
    SINGLE-DEVICE unfaulted twin (cross-layout and cross-fault at
    once)."""
    from ggrs_tpu.parallel.mesh import make_session_mesh

    _telemetry(tmp_path)
    try:
        ticks = 50
        plan = FaultPlan(9, ticks, persist_dispatch=True)
        host_f, matches, inj, desyncs = build_fleet(
            resident=True, mesh=make_session_mesh(8), ticks=ticks,
            plan=plan, victims_matches=(0, 1), sdc_audit_every=2, seed=23,
        )
        host_t, _, _, desyncs_t = build_fleet(
            resident=False, ticks=ticks, sdc_audit_every=2, seed=23,
        )
        for kind in FAULT_KINDS:
            if kind == "checkpoint_corrupt":
                continue  # needs a checkpoint call; covered above
            assert inj.fired[kind] >= 1, (kind, inj.fired)
        poisoned = host_f.take_quarantines()
        assert any(p.reason == "sdc_audit" for p in poisoned)
        assert not survivor_desyncs(desyncs, host_f, matches, {0, 1})
        assert not desyncs_t
        assert_survivors_bitexact(host_f, host_t, matches, {0, 1})
    finally:
        _telemetry_off()


# ----------------------------------------------------------------------
# focused arms (fast: tier-1)
# ----------------------------------------------------------------------


def test_fault_plan_is_pure_function_of_seed():
    a = FaultPlan(7, 100)
    b = FaultPlan(7, 100)
    assert a.section() == b.section()
    assert FaultPlan(8, 100).section() != a.section()
    kinds = {f.kind for f in a.all_faults()}
    assert kinds == set(FAULT_KINDS)
    many = FaultPlan(7, 100, events_per_kind=3)
    assert len(many.all_faults()) == 3 * len(FAULT_KINDS)


def test_transient_dispatch_raise_retries_bitexact():
    """A one-shot dispatch raise (worlds untouched) is absorbed by one
    retry: no quarantine, no desync, the WHOLE fleet bit-exact vs an
    unfaulted twin."""
    plan = FaultPlan(
        3, 30, kinds=("dispatch_raise",), events_per_kind=2,
        persist_dispatch=False,
    )
    host_f, matches, inj, desyncs = build_fleet(
        resident=False, sessions=8, ticks=30, plan=plan,
    )
    host_t, _, _, desyncs_t = build_fleet(
        resident=False, sessions=8, ticks=30,
    )
    assert inj.fired["dispatch_raise"] == 2
    assert host_f.device_faults >= 2
    assert host_f.quarantines_total == 0
    assert not desyncs and not desyncs_t
    assert_survivors_bitexact(host_f, host_t, matches, set())


def test_persistent_dispatch_raise_quarantines_culprit_only(tmp_path):
    """A fault pinned on one slot: the culprit is quarantined (typed,
    forensics), survivors re-dispatch bit-exactly."""
    _telemetry(tmp_path)
    try:
        plan = FaultPlan(
            4, 30, kinds=("dispatch_raise",), persist_dispatch=True,
        )
        host_f, matches, inj, desyncs = build_fleet(
            resident=False, sessions=8, ticks=30, plan=plan,
            victims_matches=(0,),
        )
        host_t, _, _, _ = build_fleet(resident=False, sessions=8, ticks=30)
        poisoned = host_f.take_quarantines()
        assert len(poisoned) == 1
        assert poisoned[0].reason == "dispatch_failed"
        assert poisoned[0].key in matches[0]
        assert poisoned[0].forensics is not None
        assert not survivor_desyncs(desyncs, host_f, matches, {0})
        assert_survivors_bitexact(host_f, host_t, matches, {0})
    finally:
        _telemetry_off()


def test_resident_drive_failures_degrade_to_dispatch_per_tick():
    """The degradation ladder's last rung: repeated drive failures flip
    the resident host to its dispatch-per-tick twin — still serving,
    still bit-exact — instead of crashing the fleet."""
    plan = FaultPlan(
        6, 40, kinds=("dispatch_raise",), events_per_kind=3,
        persist_dispatch=False,
    )
    host_f, matches, inj, desyncs = build_fleet(
        resident=True, sessions=8, ticks=40, plan=plan,
        drive_failure_limit=3,
    )
    host_t, _, _, _ = build_fleet(resident=True, sessions=8, ticks=40)
    assert inj.fired["dispatch_raise"] == 3
    assert host_f._resident_degraded
    assert host_f.degrades >= 1
    assert host_f.quarantines_total == 0
    assert not desyncs
    section = host_f._host_section()
    assert section["resident"]["degraded"] is True
    assert_survivors_bitexact(host_f, host_t, matches, set())


def test_sdc_bitflip_detected_within_sampling_bound(tmp_path):
    """One injected ring-row bit flip: the audit lane's at-rest sweep
    catches it within sdc_audit_every ticks of the flip and
    quarantines the slot with reason sdc_audit."""
    _telemetry(tmp_path)
    try:
        flip_tick = 12
        plan = FaultPlan(2, 13, kinds=())
        plan._by_tick = {flip_tick: [Fault(flip_tick, "slot_bitflip")]}
        caught_at = []

        def collect(t, host):
            if host.quarantines_total and not caught_at:
                caught_at.append(t)

        host, matches, inj, desyncs = build_fleet(
            resident=False, sessions=4, ticks=24, plan=plan,
            victims_matches=(0,), sdc_audit_every=2, collect=collect,
        )
        assert inj.fired["slot_bitflip"] == 1
        poisoned = host.take_quarantines()
        assert len(poisoned) == 1
        assert poisoned[0].reason == "sdc_audit"
        assert poisoned[0].key == inj.bitflips[0]["key"]
        assert caught_at and caught_at[0] - flip_tick <= 2 + 1
        assert host.audits_sampled > 0
        assert host.audit_mismatches == 1
    finally:
        _telemetry_off()


@pytest.mark.parametrize("resident", [True, False])
def test_kill_mid_harvest_checkpoint_completes_or_rolls_back(
    tmp_path, resident
):
    """A checkpoint racing an in-flight checksum batch under an injected
    harvest timeout: the export blocks-and-retries, so the checkpoint
    file is complete and loadable (never torn, never silently skipped)
    and the host keeps serving after."""
    plan = FaultPlan(2, 22, kinds=())
    # arm a harvest timeout right before the mid-run checkpoint fires,
    # while the resident arm's fill cycle holds an unforced
    # _FutureChecksumBatch
    plan._by_tick = {14: [Fault(14, "harvest_timeout")] * 2}
    path = str(tmp_path / f"mid_harvest_{resident}.npz")
    host, matches, inj, desyncs = build_fleet(
        resident=resident, sessions=4, ticks=24, plan=plan,
        checkpoint_at=14, checkpoint_path=path,
    )
    assert inj.fired["harvest_timeout"] >= 1
    assert host.harvest_timeouts >= 1
    assert not desyncs
    from ggrs_tpu.tpu.backend import MultiSessionDeviceCore

    restored = MultiSessionDeviceCore.restore(
        path, ExGame(num_players=4, num_entities=ENTITIES)
    )
    assert restored.capacity == host.device.capacity


def test_mailbox_storm_degrades_to_extra_drives_never_drops():
    """An injected commit overflow storm: every stormed stage degrades
    to an extra driver dispatch; inputs are never dropped and the fleet
    stays bit-exact vs the unstormed twin."""
    plan = FaultPlan(8, 30, kinds=("mailbox_storm",), storm_len=6)
    host_f, matches, inj, desyncs = build_fleet(
        resident=True, sessions=8, ticks=30, plan=plan,
    )
    host_t, _, _, _ = build_fleet(resident=True, sessions=8, ticks=30)
    assert inj.fired["mailbox_storm"] == 6
    assert host_f.device.mailbox.overflows >= 6
    assert not desyncs
    assert_survivors_bitexact(host_f, host_t, matches, set())


def test_typed_errors_replace_runtime_asserts():
    from ggrs_tpu.serve.migrate import HostGroup

    clock = FakeClock()
    host = SessionHost(
        ExGame(num_players=2, num_entities=ENTITIES),
        max_prediction=8, num_players=2, max_sessions=2, clock=clock,
        resident=True, resident_ticks=2,
    )
    mbox = host.device.mailbox
    row = host.device.core.pad_tick_row()
    mbox.stage(0, row, 1, True)
    mbox.stage(0, row, 1, True)
    with pytest.raises(MailboxLaneFull) as exc:
        mbox.stage(0, row, 1, True)
    assert exc.value.lane == 0 and exc.value.depth == 2
    group = HostGroup([host], clock=clock)
    with pytest.raises(InvalidRequest):
        group.restore_host(0, "/nonexistent.npz")  # never killed
    # typed DeviceDispatchFailed carries its containment context
    err = DeviceDispatchFailed("boom", op="megabatch", slots=(3,),
                              injected=True)
    assert err.slots == (3,) and err.injected and "megabatch" in str(err)


def test_invariant_monitor_trips_on_wedged_lane(tmp_path):
    """A RUNNING lane that stops advancing past wedge_limit_ticks trips
    the lane_wedged invariant: typed, with a forensics bundle — the
    PR 8 WAN-soak bug class, watched deliberately."""
    _telemetry(tmp_path)
    try:
        clock = FakeClock()
        net = InMemoryNetwork(clock, latency_ms=10, jitter_ms=0, loss=0.0)
        host = SessionHost(
            ExGame(num_players=4, num_entities=ENTITIES),
            max_prediction=8, num_players=4, max_sessions=6,
            clock=clock, idle_timeout_ms=0, wedge_limit_ticks=12,
        )
        matches = build_matches(host, net, clock, sessions=4, seed=3)
        sync_fleet(host, matches, clock)
        scripts = make_scripts(matches, 40, seed=3)
        for t in range(40):
            if t == 8:
                # blackhole peer 0 both ways: its match wedges at the
                # prediction gate while staying RUNNING
                net.set_blackhole([(0, 0)], True)
            for m, keys in enumerate(matches):
                for k, key in enumerate(keys):
                    host.submit_input(key, k, bytes([scripts[(m, k)][t]]))
            host.tick()
            clock.advance(FRAME_MS)
        trips = [
            e for e in host.invariant_trips
            if e.invariant == "lane_wedged"
        ]
        assert trips, "wedged lane never tripped the monitor"
        assert isinstance(trips[0], InvariantViolation)
        assert trips[0].forensics is not None
    finally:
        _telemetry_off()


# ----------------------------------------------------------------------
# fleet x resident (satellite): agents on the resident loop, SIGKILL
# restore + cross-process migration bit-exact, quarantine mini-failover
# ----------------------------------------------------------------------


def _fleet_rig(tmp_path, *, resident, n_agents=2, checkpoint_every=8):
    from ggrs_tpu.fleet.agent import AgentCore
    from ggrs_tpu.fleet.director import Director
    from ggrs_tpu.fleet.wire import conn_pair

    clock = FakeClock()
    game = ExGame(num_players=2, num_entities=ENTITIES)
    director = Director(
        clock=clock, base_dir=str(tmp_path), seed=1,
        hb_interval_ms=50, suspicion_misses=4,
    )
    agents = []
    for i in range(n_agents):
        a_conn, d_conn = conn_pair()
        core = AgentCore(
            game, base_dir=str(tmp_path), clock=clock,
            max_sessions=8, num_players=2, hb_interval_ms=50,
            checkpoint_every=checkpoint_every, label=f"a{i}",
            resident=resident if i == 0 else False, resident_ticks=4,
        )
        core.attach_conn(a_conn)
        director.attach_conn(d_conn)
        core.start()
        agents.append(core)

    def pump(n=1, adv=10):
        for _ in range(n):
            for a in agents:
                a.step()
            director.step()
            clock.advance(adv)

    director.on_wait = lambda: pump(1, 2)
    pump(10)
    assert len(director.hosts) == n_agents
    return clock, director, agents, pump


def _drive_done(agents, pump, max_steps=4000):
    for _ in range(max_steps):
        pump(1)
        if all(
            i.done or i.failed
            for c in agents if c.terminated is None
            for i in c.islands.values()
        ):
            return
    raise AssertionError("islands failed to finish")


@pytest.mark.slow
def test_agent_resident_twin_parity_and_migration(tmp_path):
    """Satellite: agent 0 runs resident=True. Both matches finish with
    histories bit-identical to the in-process unfaulted twin, and a
    cross-process migration OUT of the resident agent (mailbox drained
    into the ticket) is observationally neutral."""
    from ggrs_tpu.fleet.chaos import compare_with_twin
    from ggrs_tpu.fleet.island import MatchSpec

    clock, director, agents, pump = _fleet_rig(tmp_path, resident=True)
    specs = [
        MatchSpec(match_id=0, players=2, ticks=48, seed=100,
                  entities=ENTITIES, wan={}),
        MatchSpec(match_id=1, players=2, ticks=48, seed=101,
                  entities=ENTITIES),
    ]
    owners = {s.match_id: director.place_match(s) for s in specs}
    # both matches onto the RESIDENT agent, then migrate one off it
    if owners[0] != 0:
        director.migrate_match(0, 0)
    if owners[1] != 0:
        director.migrate_match(1, 0)
    for _ in range(20):
        pump(1)
    director.migrate_match(0, 1)  # resident -> non-resident, mid-match
    _drive_done(agents, pump)
    reports = director.collect_reports()
    parity = compare_with_twin(specs, reports, set())
    assert parity["clean_exact"], parity


@pytest.mark.slow
def test_agent_resident_sigkill_restore_bitexact(tmp_path):
    """Satellite: a resident agent's crash checkpoint restores on a
    FRESH (non-resident) agent bit-exactly — the SIGKILL-restore path
    out of resident mode, in-process twin of the process soak."""
    from ggrs_tpu.fleet.agent import AgentCore
    from ggrs_tpu.fleet.chaos import compare_with_twin
    from ggrs_tpu.fleet.island import MatchSpec
    from ggrs_tpu.fleet.ticket import loads_ticket, read_ticket_file

    clock, director, agents, pump = _fleet_rig(
        tmp_path, resident=True, n_agents=1, checkpoint_every=4
    )
    spec = MatchSpec(match_id=0, players=2, ticks=48, seed=42,
                     entities=ENTITIES, wan={})
    director.place_match(spec)
    for _ in range(30):
        pump(1)
    assert agents[0].checkpoints_written > 0
    # "SIGKILL": drop the agent; restore its islands from the on-disk
    # ticket into a fresh NON-resident host (cross-arm restore)
    path = agents[0].checkpoint_path()
    entries, meta = loads_ticket(read_ticket_file(path))
    fresh = AgentCore(
        ExGame(num_players=2, num_entities=ENTITIES),
        base_dir=str(tmp_path), clock=clock, max_sessions=8,
        num_players=2, label="fresh", resident=False,
    )
    from ggrs_tpu.fleet.ticket import import_islands

    for island in import_islands(fresh.host, entries):
        fresh.islands[island.spec.match_id] = island
    agents[0].islands.clear()  # the killed incarnation is gone
    for _ in range(4000):
        fresh.step()
        clock.advance(10)
        if all(i.done for i in fresh.islands.values()):
            break
    report = {
        0: {
            "islands": {
                "0": {
                    **fresh.islands[0].section(),
                    "histories": {
                        str(k): {str(f): c for f, c in h.items()}
                        for k, h in fresh.islands[0].histories().items()
                    },
                    "digest": fresh.islands[0].state_digest(fresh.host),
                    "spread": False,
                }
            }
        }
    }
    parity = compare_with_twin([spec], report, set())
    assert parity["clean_exact"], parity


@pytest.mark.slow
def test_agent_quarantine_mini_failover_rebuilds_from_ticket(tmp_path):
    """Tentpole x fleet: a quarantined slot on an agent tears down the
    owning match and REBUILDS it from the last crash-checkpoint ticket
    (the PR 11 adopt machinery as a mini-failover); the heartbeat
    reports the outcome to the director."""
    _telemetry(tmp_path)
    try:
        from ggrs_tpu.fleet.island import MatchSpec

        clock, director, agents, pump = _fleet_rig(
            tmp_path, resident=True, n_agents=1, checkpoint_every=4
        )
        agent = agents[0]
        agent.host._audit_every = 0  # quarantine via direct poison below
        spec = MatchSpec(match_id=0, players=2, ticks=64, seed=9,
                         entities=ENTITIES, wan={})
        director.place_match(spec)
        for _ in range(24):
            pump(1)
        assert agent.checkpoints_written > 0
        # poison one of the match's slots the direct way (the injector
        # path is pinned elsewhere): quarantine fires the mini-failover
        key = next(iter(agent.islands[0].keys.values()))
        agent.host.quarantine(key, "sdc_audit")
        pump(1)
        assert agent.quarantines.get(0) == "rebuilt"
        island = agent.islands[0]
        assert island.keys and not island.failed
        # the rebuilt match finishes clean
        _drive_done(agents, pump)
        assert island.desyncs == 0
        # ... and the director heard about it
        pump(20)
        hr = director.hosts[agent.host_id]
        assert hr.quarantines.get("0") == "rebuilt"
    finally:
        _telemetry_off()
