"""Pallas fused-SyncTest kernel vs the XLA scan: full-carry bit parity.

Runs the kernel in interpreter mode (tests execute on the CPU mesh); the
real-TPU execution of the same kernel is exercised by bench.py and the
driver's hardware runs.
"""

import numpy as np
import pytest

import jax
import jax.tree_util as jtu

from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.tpu import TpuSyncTestSession

P = 2


def drive(backend, script, entities, check_distance, batches):
    sess = TpuSyncTestSession(
        ExGame(P, entities),
        num_players=P,
        check_distance=check_distance,
        flush_interval=10_000,
        backend=backend,
    )
    t = script.shape[0] // batches
    for i in range(batches):
        sess.advance_frames(script[i * t : (i + 1) * t])
    return sess


def assert_carry_equal(a, b):
    la = jtu.tree_leaves_with_path(jax.device_get(a))
    lb = jtu.tree_leaves(jax.device_get(b))
    assert len(la) == len(lb)
    for (path, x), y in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=jtu.keystr(path)
        )


@pytest.mark.parametrize("check_distance,entities", [(2, 256), (8, 512)])
def test_pallas_carry_parity_with_xla(check_distance, entities):
    rng = np.random.default_rng(5)
    script = rng.integers(0, 16, size=(60, P, 1), dtype=np.uint8)
    xla = drive("xla", script, entities, check_distance, batches=3)
    pls = drive("pallas-interpret", script, entities, check_distance, batches=3)
    assert_carry_equal(xla.carry, pls.carry)
    xla.check()
    pls.check()


def test_pallas_detects_injected_divergence():
    """Corrupt a ring snapshot between batches: the in-kernel first-seen
    history must latch a mismatch, like the XLA path."""
    from ggrs_tpu.errors import MismatchedChecksum

    rng = np.random.default_rng(6)
    script = rng.integers(0, 16, size=(40, P, 1), dtype=np.uint8)
    sess = TpuSyncTestSession(
        ExGame(P, 256),
        num_players=P,
        check_distance=4,
        flush_interval=10_000,
        backend="pallas-interpret",
    )
    sess.advance_frames(script[:20])
    sess.check()  # clean so far
    ring = dict(sess.carry["ring"])
    slot = (sess.current_frame - 4) % sess.ring_len
    ring["pos"] = ring["pos"].at[slot, 0, 0].add(7)
    sess.carry = {**sess.carry, "ring": ring}
    sess.advance_frames(script[20:])
    with pytest.raises(MismatchedChecksum):
        sess.check()


def test_pallas_rejects_unsupported_configs():
    with pytest.raises(AssertionError):
        TpuSyncTestSession(
            ExGame(P, 100),  # not 128-aligned
            num_players=P,
            check_distance=2,
            backend="pallas-interpret",
        )


def test_pallas_rejects_vmem_overflow_configs():
    """Worlds whose plane windows exceed the validated VMEM budget must be
    rejected at construction (beyond it Mosaic has been observed to
    miscompile silently), sending callers to the XLA backend."""
    import pytest

    from ggrs_tpu.tpu.pallas_core import PallasSyncTestCore

    with pytest.raises(ValueError, match="VMEM-resident"):
        PallasSyncTestCore(ExGame(P, 524288), num_players=P, check_distance=2)
    # the validated large config constructs fine
    PallasSyncTestCore(ExGame(P, 262144), num_players=P, check_distance=2)


def test_legacy_three_arg_adapter_still_runs():
    """Back-compat: a third-party adapter registered with the
    pre-reduction-phase step signature (planes, inputs, ctx) must keep
    working on the whole-batch kernel (it calls the bare 3-arg form for
    adapters without a reduction phase)."""
    import numpy as np

    from ggrs_tpu.models.ex_game import ExGame
    from ggrs_tpu.tpu.pallas_core import ExGamePlanes, register_adapter

    class LegacyGame(ExGame):
        pass

    class LegacyPlanes(ExGamePlanes):
        def step(self, pl, inputs, ctx):  # old signature: no red kwarg
            return super().step(pl, inputs, ctx)

    register_adapter(LegacyGame, LegacyPlanes)
    sess = TpuSyncTestSession(
        LegacyGame(P, 256),
        num_players=P,
        check_distance=2,
        flush_interval=10_000,
        backend="pallas-interpret",
    )
    rng = np.random.default_rng(3)
    sess.advance_frames(rng.integers(0, 16, size=(12, P, 1), dtype=np.uint8))
    sess.check()
