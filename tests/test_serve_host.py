"""SessionHost: admission control, scheduling/backpressure, lifecycle,
cross-session megabatch correctness, and the 64-session loadgen soak.

The parity strategy mirrors the backend suite: the same deterministic
request stream through a solo TpuRollbackBackend and through a hosted
lane must produce bit-identical saved checksums — any divergence is the
megabatch path's fault. The soak then scales that to a fleet: dozens of
lossy-network matches multiplexed through ONE stacked device core, with
desync detection as the bit-parity referee (and a tamper test proving
the referee actually blows the whistle)."""

import random

import numpy as np
import pytest

from ggrs_tpu import (
    DesyncDetected,
    PlayerType,
    SaveGameState,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.errors import HostFull, InvalidRequest
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.serve import SessionHost
from ggrs_tpu.serve.loadgen import run_loadgen
from ggrs_tpu.tpu import TpuRollbackBackend
from ggrs_tpu.utils.clock import FakeClock

ENTITIES = 16


def make_host(clock=None, *, max_sessions=4, num_players=2, **kw):
    return SessionHost(
        ExGame(num_players=num_players, num_entities=ENTITIES),
        max_prediction=8,
        num_players=num_players,
        max_sessions=max_sessions,
        clock=clock or FakeClock(),
        **kw,
    )


def solo_session(net, addr, *, players=2):
    """A local-only P2P session (every handle local): RUNNING immediately,
    no network dependency — the deterministic lifecycle workhorse."""
    b = SessionBuilder(input_size=1).with_num_players(players)
    for h in range(players):
        b = b.add_player(PlayerType.local(), h)
    return b.start_p2p_session(net.socket(addr))


def drive_solo(host, key, session, ticks, *, script=lambda t, h: (t * 3 + h) % 16):
    for t in range(ticks):
        for h in session.local_player_handles():
            host.submit_input(key, h, bytes([script(t, h)]))
        host.tick()
        host.clock.advance(16)


# ----------------------------------------------------------------------
# admission control
# ----------------------------------------------------------------------


def test_admission_rejects_at_max_sessions():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = make_host(clock, max_sessions=2)
    k0 = host.attach(solo_session(net, "a"))
    host.attach(solo_session(net, "b"))
    with pytest.raises(HostFull):
        host.attach(solo_session(net, "c"))
    assert host.sessions_rejected == 1
    # detaching frees the slot: admission recovers
    host.detach(k0)
    host.attach(solo_session(net, "d"))
    assert host.active_sessions == 2


def test_attach_rejects_double_hosting_and_layout_mismatch():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = make_host(clock)
    sess = solo_session(net, "a")
    host.attach(sess)
    with pytest.raises(InvalidRequest):
        host.attach(sess)  # already hosted
    wide = solo_session(net, "w", players=2)
    narrow_host = make_host(FakeClock(), num_players=2)
    too_wide = SessionBuilder(input_size=1).with_num_players(3)
    for h in range(3):
        too_wide = too_wide.add_player(PlayerType.local(), h)
    with pytest.raises(InvalidRequest):
        narrow_host.attach(too_wide.start_p2p_session(net.socket("t")))
    narrow_host.attach(wide)  # exactly at the layout: fine
    # input_size must match the host game for EVERY session kind —
    # validated at admission, not discovered as a parse crash mid-tick
    fat_spec = (
        SessionBuilder(input_size=2)
        .with_num_players(2)
        .with_clock(clock)
        .start_spectator_session("game", net.socket("fatspec"))
    )
    with pytest.raises(InvalidRequest):
        host.attach(fat_spec)
    fat_p2p = SessionBuilder(input_size=2).with_num_players(2)
    for h in range(2):
        fat_p2p = fat_p2p.add_player(PlayerType.local(), h)
    with pytest.raises(InvalidRequest):
        host.attach(fat_p2p.start_p2p_session(net.socket("fatp2p")))
    # only fresh sessions: the lane's frame bookkeeping starts at 0
    stale = solo_session(net, "stale")
    stale.add_local_input(0, b"\x01")
    stale.add_local_input(1, b"\x01")
    from stubs import GameStub

    GameStub().handle_requests(stale.advance_frame())
    with pytest.raises(InvalidRequest):
        host.attach(stale)


# ----------------------------------------------------------------------
# megabatch parity vs the solo backend
# ----------------------------------------------------------------------


def checksum_getters(requests):
    return [
        (r.frame, r.cell.checksum_getter())
        for r in requests
        if isinstance(r, SaveGameState)
    ]


def test_hosted_checksums_match_solo_backend():
    """Strict bitwise witness: identical scripts through (a) the solo
    backend and (b) a hosted lane sharing its megabatch with a decoy;
    every saved frame's checksum must match."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)

    script = lambda t, h: (t * 3 + h) % 16
    ticks = 24

    # (a) solo: session requests fulfilled by TpuRollbackBackend
    ref_sess = solo_session(net, "ref")
    ref_backend = TpuRollbackBackend(
        ExGame(num_players=2, num_entities=ENTITIES),
        max_prediction=8,
        num_players=2,
    )
    ref_getters = []
    for t in range(ticks):
        for h in (0, 1):
            ref_sess.add_local_input(h, bytes([script(t, h)]))
        reqs = ref_sess.advance_frame()
        ref_backend.handle_requests(reqs)
        ref_getters += checksum_getters(reqs)

    # (b) hosted: intercept the hosted session's requests via the lane's
    # staged saves — bind the same checksum_getter surface
    host = make_host(clock)
    sess = solo_session(net, "a")
    decoy = solo_session(net, "b")
    key = host.attach(sess)
    dkey = host.attach(decoy)
    tapped = []
    orig_advance = sess.advance_frame

    def tapped_advance():
        reqs = orig_advance()
        tapped.append(reqs)
        return reqs

    sess.advance_frame = tapped_advance
    got = []
    for t in range(ticks):
        for h in (0, 1):
            host.submit_input(key, h, bytes([script(t, h)]))
            host.submit_input(dkey, h, bytes([(t * 11 + 2 + h) % 16]))
        host.tick()
        clock.advance(16)
        # getters must be captured per tick, while each save's cell still
        # holds THIS frame's binding (ring slots are reused every
        # ring_len frames; checksum_getter is only stable from then on)
        for reqs in tapped:
            got += checksum_getters(reqs)
        tapped.clear()

    ref_vals = [(f, g()) for f, g in ref_getters]
    got_vals = [(f, g()) for f, g in got]
    assert ref_vals == got_vals
    # and the live world is bit-identical too
    solo_state = ref_backend.state_numpy()
    lane_state = host.device.state_numpy(host._lanes[key].slot)
    for k in solo_state:
        np.testing.assert_array_equal(
            np.asarray(solo_state[k]), np.asarray(lane_state[k]),
            err_msg=f"state[{k}]",
        )


# ----------------------------------------------------------------------
# backpressure
# ----------------------------------------------------------------------


def test_backpressure_queues_ready_sessions_in_arrival_order():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = make_host(clock, max_sessions=4, max_inflight_rows=2)
    keys = [host.attach(solo_session(net, f"s{i}")) for i in range(4)]
    for key in keys:
        for h in (0, 1):
            host.submit_input(key, h, b"\x01")
    # pin the device window shut: nothing retires, so the budget is 0 and
    # every ready session must queue
    real_poll = host.device.poll_retired
    host.device.poll_retired = lambda: host.max_inflight_rows
    host.tick()
    assert host.queue_depth == 4
    assert all(host._lanes[k].rows for k in keys)
    # reopen the window: queued rows dispatch in arrival order
    host.device.poll_retired = real_poll
    host.tick()
    assert host.queue_depth == 0
    assert all(host._lanes[k].current_frame == 1 for k in keys)


# ----------------------------------------------------------------------
# lifecycle: idle eviction, disconnect GC, graceful drain
# ----------------------------------------------------------------------


def test_idle_eviction_under_fake_clock():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = make_host(clock, idle_timeout_ms=1_000)
    busy = host.attach(solo_session(net, "busy"))
    idle = host.attach(solo_session(net, "idle"))
    idle_sess = host.session(idle)
    for t in range(80):
        for h in (0, 1):
            host.submit_input(busy, h, b"\x02")
        host.tick()
        clock.advance(16)
    assert host.sessions_evicted == 1
    assert idle not in host.keys()
    assert busy in host.keys()
    assert idle_sess.host_key is None  # detach hook ran
    # the freed slot is reusable
    host.attach(solo_session(net, "fresh"))


def test_disconnect_gc_reclaims_dead_matches():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = make_host(clock, idle_timeout_ms=0)

    def peer(addr, other, handle):
        return (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_clock(clock)
            .with_rng(random.Random(handle + 5))
            .add_player(PlayerType.local(), handle)
            .add_player(PlayerType.remote(other), 1 - handle)
            .start_p2p_session(net.socket(addr))
        )

    s0, s1 = peer("a", "b", 0), peer("b", "a", 1)
    k0 = host.attach(s0)
    host.attach(s1)
    for _ in range(200):
        host.tick()
        clock.advance(20)
        if all(
            host.session(k).current_state() == SessionState.RUNNING
            for k in host.keys()
        ):
            break
    else:
        raise AssertionError("match failed to synchronize")
    s0.disconnect_player(1)
    for _ in range(300):
        host.tick()
        clock.advance(20)
        if not host.keys():
            break
    # s0 GCs as soon as its only remote is disconnected; s1's endpoint to
    # s0 times out (disconnect_timeout) and then GCs too
    assert k0 not in host.keys()
    assert host.sessions_gced >= 1
    assert not host.keys(), f"undead sessions: {host.keys()}"


def test_graceful_drain_flushes_fence_and_checkpoints(tmp_path):
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = make_host(clock, max_sessions=3, max_inflight_rows=1)
    keys = [host.attach(solo_session(net, f"s{i}")) for i in range(3)]
    drive_solo(host, keys[0], host.session(keys[0]), 3)
    # stage rows that CANNOT dispatch (window pinned shut), then drain:
    # it must flush them anyway
    for key in keys:
        for h in (0, 1):
            host.submit_input(key, h, b"\x03")
    real_poll = host.device.poll_retired
    host.device.poll_retired = lambda: host.max_inflight_rows
    host.tick()
    host.device.poll_retired = real_poll
    assert host.queue_depth > 0
    path = str(tmp_path / "host.npz")
    summary = host.drain(checkpoint_path=path)
    assert host.queue_depth == 0
    assert summary["queue_depth"] == 0
    assert summary["checkpoint"] == path
    # drained host admits nobody
    with pytest.raises(HostFull):
        host.attach(solo_session(net, "late"))
    # the checkpoint restores bit-exactly
    from ggrs_tpu.tpu.backend import MultiSessionDeviceCore

    restored = MultiSessionDeviceCore.restore(
        path, ExGame(num_players=2, num_entities=ENTITIES)
    )
    a = host.device.state_numpy(host._lanes[keys[0]].slot)
    b = restored.state_numpy(host._lanes[keys[0]].slot)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


# ----------------------------------------------------------------------
# spectators ride the same megabatch
# ----------------------------------------------------------------------


def test_spectator_lane_advances_on_host():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = make_host(clock, max_sessions=3, num_players=2)
    p2p = (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_clock(clock)
        .with_rng(random.Random(31))
        .add_player(PlayerType.local(), 0)
        .add_player(PlayerType.local(), 1)
        .add_player(PlayerType.spectator("spec"), 2)
        .start_p2p_session(net.socket("game"))
    )
    spec = (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_clock(clock)
        .with_rng(random.Random(32))
        .start_spectator_session("game", net.socket("spec"))
    )
    pk = host.attach(p2p)
    sk = host.attach(spec)
    for t in range(60):
        for h in (0, 1):
            host.submit_input(pk, h, bytes([(t + h) % 16]))
        host.tick()
        clock.advance(16)
    spec_lane = host._lanes[sk]
    assert spec.current_state() == SessionState.RUNNING
    assert spec_lane.current_frame > 10, "spectator never advanced on host"
    assert spec_lane.kind == "spectator"


# ----------------------------------------------------------------------
# the referee is real: tampering trips desync detection across the host
# ----------------------------------------------------------------------


def test_tampered_slot_trips_desync_detection():
    """Reset one peer's device slot mid-run: its world diverges from its
    peer's, so the next checksum exchange must surface DesyncDetected —
    proving the soak's zero-desync assertion is non-vacuous."""
    rep = run_loadgen(
        sessions=2, ticks=30, entities=ENTITIES, seed=5,
        loss=0.0, jitter_ms=0, latency_ms=20,
    )
    assert rep["desyncs"] == 0  # clean baseline on this seed
    host = rep["_host"]
    clock = host.clock
    # tamper one lane's world, then keep the match running
    keys = host.keys()
    lane = host._lanes[keys[0]]
    host.device.reset_slot(lane.slot)
    desyncs = 0
    for t in range(80):
        for key in keys:
            k = host._lanes[key]
            for h in k.local_handles:
                host.submit_input(key, h, bytes([(t + h) % 16]))
        events = host.tick()
        for evs in events.values():
            desyncs += sum(isinstance(e, DesyncDetected) for e in evs)
        clock.advance(16)
    assert desyncs > 0, "device-state tamper went undetected"


# ----------------------------------------------------------------------
# the acceptance soak: 64 sessions, lossy network, zero desyncs
# ----------------------------------------------------------------------


def test_loadgen_soak_64_sessions_lossy():
    from ggrs_tpu.obs import GLOBAL_TELEMETRY

    GLOBAL_TELEMETRY.enabled = True
    try:
        rep = run_loadgen(
            sessions=64,
            ticks=60,
            entities=ENTITIES,
            seed=1,
            loss=0.05,
            latency_ms=20,
            jitter_ms=10,
        )
        GLOBAL_TELEMETRY.enabled = False
        _soak_assertions(rep)
    finally:
        # test isolation even when an assertion above fails: the soak ran
        # with telemetry ON, and nonzero counters/events left in the
        # process-wide registry trip later tests asserting a quiet
        # disabled-telemetry baseline (observed: test_telemetry.
        # test_disabled_telemetry_records_nothing sharing the process)
        GLOBAL_TELEMETRY.enabled = False
        GLOBAL_TELEMETRY.reset()


def _soak_assertions(rep):
    from ggrs_tpu.obs import GLOBAL_TELEMETRY

    host = rep.pop("_host")
    assert rep["sessions"] >= 64
    assert rep["desyncs"] == 0, f"soak desynced: {rep}"
    # the zero-desync claim must be backed by real comparisons
    assert rep["checksums_published"] > 0
    # cross-session coalescing actually engages
    assert rep["mean_megabatch_rows"] > 1.0
    assert rep["max_bucket"] >= 32
    # every session made it through (throttling may shave a few frames)
    assert rep["min_frame"] >= rep["ticks"] - 8
    # the shared plan cache stays canonical: a 64-session fleet must not
    # compile per-session programs — request-segment signatures stay a
    # couple dozen shapes, and megabatch programs stay inside the
    # (row bucket x depth bucket + fast) grid depth routing guarantees
    mega = host.device.megabatch_programs()
    n_row_sigs = len(host.device.plan_cache.signatures) - len(mega)
    assert n_row_sigs <= 24, n_row_sigs
    assert len(mega) <= host.device.dispatch_bucket_budget(), sorted(mega)
    # rollback depth stayed inside the prediction window
    hist = GLOBAL_TELEMETRY.registry.get("ggrs_rollback_depth_frames")
    snap = hist.snapshot()["values"][""]
    assert snap["count"] > 0, "soak never rolled back: not a rollback test"
    beyond = sum(
        c for le, c in snap["buckets"].items()
        if le != "+Inf" and float(le) > 8
    ) + snap["buckets"]["+Inf"]
    assert beyond == 0, f"rollback depth escaped the window: {snap}"
    host.drain()
