"""Datagram-size bounds at the transport seam (VERDICT follow-up: the old
RECV_BUFFER_SIZE = 4096 silently truncated any datagram that outgrew it —
recvfrom() drops the excess without an error). The buffer now covers the
largest UDP payload, and every send path asserts the bound eagerly so an
overgrown message fails at the ENCODER, not as a mystery truncation on the
receiving peer."""

import pytest

from ggrs_tpu.errors import InvalidRequest
from ggrs_tpu.network.sockets import (
    MAX_DATAGRAM_SIZE,
    RECV_BUFFER_SIZE,
    InMemoryNetwork,
    UdpNonBlockingSocket,
    check_datagram_size,
)
from ggrs_tpu.utils.clock import FakeClock


def test_buffer_covers_udp_payloads():
    # 65507 is the largest payload UDP itself can carry; anything the
    # protocol can legally send must now survive recvfrom intact — and
    # the send bound must not admit datagrams UDP itself would reject
    assert RECV_BUFFER_SIZE >= 65507
    assert MAX_DATAGRAM_SIZE == 65507


def test_check_datagram_size_boundary():
    assert check_datagram_size(b"x" * MAX_DATAGRAM_SIZE) is not None
    # a real exception, not an assert: the guard must survive python -O
    with pytest.raises(InvalidRequest):
        check_datagram_size(b"x" * (MAX_DATAGRAM_SIZE + 1))


def test_in_memory_network_enforces_the_bound():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    a, b = net.socket("a"), net.socket("b")
    a.send_wire(b"y" * MAX_DATAGRAM_SIZE, "b")
    clock.advance(1)
    [(src, wire)] = b.receive_all_wire()
    assert src == "a" and len(wire) == MAX_DATAGRAM_SIZE
    with pytest.raises(InvalidRequest):
        a.send_wire(b"y" * (MAX_DATAGRAM_SIZE + 1), "b")


def test_udp_round_trip_past_old_truncation_boundary():
    """A real-loopback datagram one byte PAST the old 4096 buffer must
    arrive bit-exact — the regression the bump exists to fix."""
    tx = UdpNonBlockingSocket(0)
    rx = UdpNonBlockingSocket(0)
    try:
        payload = bytes((i * 7 + 3) & 0xFF for i in range(4097))
        tx.send_wire(payload, ("127.0.0.1", rx.local_port))
        got = []
        for _ in range(200):
            got = rx.receive_all_wire()
            if got:
                break
        assert got, "datagram never arrived on loopback"
        [(_, wire)] = got
        assert wire == payload  # full length, byte-exact: no truncation
    finally:
        tx.close()
        rx.close()
