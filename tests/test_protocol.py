"""PeerEndpoint state machine in isolation over the fault-injecting virtual
network — the protocol-level coverage the reference lacks (SURVEY.md §4)."""

import random

from ggrs_tpu.frame_info import PlayerInput
from ggrs_tpu.network.protocol import (
    NUM_SYNC_PACKETS,
    EvDisconnected,
    EvInput,
    EvNetworkInterrupted,
    EvNetworkResumed,
    EvSynchronized,
    PeerEndpoint,
)
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.sync_layer import ConnectionStatus
from ggrs_tpu.utils.clock import FakeClock


def make_pair(clock, net, **net_kwargs):
    sock_a = net.socket("a")
    sock_b = net.socket("b")
    kwargs = dict(
        num_players=2,
        local_players=1,
        max_prediction=8,
        disconnect_timeout_ms=2000,
        disconnect_notify_start_ms=500,
        fps=60,
        input_size=1,
        clock=clock,
    )
    ep_a = PeerEndpoint(handles=[1], peer_addr="b", rng=random.Random(1), **kwargs)
    ep_b = PeerEndpoint(handles=[0], peer_addr="a", rng=random.Random(2), **kwargs)
    return (ep_a, sock_a), (ep_b, sock_b)


def pump(pairs, status, clock, steps=1, advance_ms=10):
    events = {id(ep): [] for ep, _ in pairs}
    for _ in range(steps):
        for ep, sock in pairs:
            for _, msg in sock.receive_all_messages():
                ep.handle_message(msg)
            events[id(ep)].extend(ep.poll(status))
            ep.send_all_messages(sock)
        clock.advance(advance_ms)
    return events


def test_sync_handshake_completes():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair(clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    events = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2 * NUM_SYNC_PACKETS)
    assert ep_a.is_running() and ep_b.is_running()
    assert any(isinstance(e, EvSynchronized) for e in events[id(ep_a)])
    assert any(isinstance(e, EvSynchronized) for e in events[id(ep_b)])


def test_sync_survives_heavy_loss():
    clock = FakeClock()
    net = InMemoryNetwork(clock, loss=0.5, seed=99)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair(clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    # retries happen on the 200ms sync timer; give it simulated seconds
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=200, advance_ms=50)
    assert ep_a.is_running() and ep_b.is_running()


def _sync(clock, net):
    pair = make_pair(clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    pair[0][0].synchronize()
    pair[1][0].synchronize()
    for _ in range(100):
        pump(list(pair), status, clock, steps=1, advance_ms=60)
        if pair[0][0].is_running() and pair[1][0].is_running():
            break
    assert pair[0][0].is_running() and pair[1][0].is_running()
    return pair, status


def test_input_transmission_under_loss_recovers_by_resend():
    clock = FakeClock()
    net = InMemoryNetwork(clock, loss=0.4, seed=7)
    ((ep_a, sock_a), (ep_b, sock_b)), status = _sync(clock, net)

    sent = []
    got = []
    for frame in range(30):
        inp = PlayerInput(frame, bytes([frame % 11]))
        sent.append(inp.buf)
        ep_a.send_input({1: inp}, status)
        evs = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2, advance_ms=120)
        got.extend(e for e in evs[id(ep_b)] if isinstance(e, EvInput))
    # tail resends: keep pumping until everything arrived
    for _ in range(50):
        evs = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=1, advance_ms=120)
        got.extend(e for e in evs[id(ep_b)] if isinstance(e, EvInput))
        if len(got) == 30:
            break

    assert [e.input.frame for e in got] == list(range(30))  # in order, no gaps
    assert [e.input.buf for e in got] == sent
    # ep_b's endpoint represents remote player 0; inputs attribute to it
    assert all(e.player == 0 for e in got)


def test_rtt_estimation():
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=40)
    ((ep_a, sock_a), (ep_b, sock_b)), status = _sync(clock, net)
    # quality reports fire on their 200ms timer; replies echo the ping time
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=20, advance_ms=50)
    assert 40 <= ep_a.round_trip_time <= 200


def test_interrupt_resume_and_disconnect():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    ((ep_a, sock_a), (ep_b, sock_b)), status = _sync(clock, net)

    # silence from b: a must emit NetworkInterrupted after 500ms
    evs_a = []
    for _ in range(8):
        for _, msg in sock_a.receive_all_messages():
            pass  # drop everything b might have queued earlier
        evs_a.extend(ep_a.poll(status))
        clock.advance(100)
    assert any(isinstance(e, EvNetworkInterrupted) for e in evs_a)
    assert not any(isinstance(e, EvDisconnected) for e in evs_a)

    # traffic resumes: NetworkResumed
    ep_b.send_input({0: PlayerInput(0, b"\x01")}, status)
    ep_b.send_all_messages(sock_b)
    evs = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=1)
    assert any(isinstance(e, EvNetworkResumed) for e in evs[id(ep_a)])

    # then full silence past the 2000ms timeout: Disconnected
    evs_a = []
    for _ in range(25):
        sock_a.receive_all_messages()
        evs_a.extend(ep_a.poll(status))
        clock.advance(100)
    assert any(isinstance(e, EvDisconnected) for e in evs_a)


def test_keep_alive_prevents_disconnect():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    pair, status = _sync(clock, net)
    # no game inputs at all, only timers: keep-alives must keep both sides up
    evs = pump(list(pair), status, clock, steps=100, advance_ms=100)
    for ep, _ in pair:
        assert ep.is_running()
        assert not any(isinstance(e, EvDisconnected) for e in evs[id(ep)])


def test_magic_filter_rejects_forged_packets():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    ((ep_a, sock_a), (ep_b, sock_b)), status = _sync(clock, net)
    from ggrs_tpu.network.messages import InputAck, Message

    before = ep_a.pending_output.copy()
    ep_a.send_input({1: PlayerInput(0, b"\x05")}, status)
    assert len(ep_a.pending_output) == 1
    # forged ack with a wrong magic must be ignored
    ep_a.handle_message(Message(magic=ep_b.magic ^ 0x5555, body=InputAck(ack_frame=5)))
    assert len(ep_a.pending_output) == 1


def test_oversized_pending_window_sends_prefix_instead_of_crashing():
    """A long un-acked window of incompressible inputs must not kill the
    session: the endpoint sends the longest prefix fitting the UDP budget."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    sock_a = net.socket("a")
    sock_b = net.socket("b")
    kwargs = dict(
        num_players=2,
        local_players=2,
        max_prediction=8,
        disconnect_timeout_ms=2000,
        disconnect_notify_start_ms=500,
        fps=60,
        input_size=8,  # 16 bytes/frame across both local players
        clock=clock,
    )
    ep_a = PeerEndpoint(handles=[0, 1], peer_addr="b", rng=random.Random(3), **kwargs)
    ep_b = PeerEndpoint(handles=[0, 1], peer_addr="a", rng=random.Random(4), **kwargs)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=12)
    assert ep_a.is_running()

    rng = random.Random(9)
    # b never acks (we just don't pump it); push 100 incompressible frames
    for frame in range(100):
        buf = bytes(rng.randrange(256) for _ in range(8))
        ep_a.send_input(
            {0: PlayerInput(frame, buf), 1: PlayerInput(frame, buf)}, status
        )
    ep_a.send_all_messages(sock_a)  # must not raise
    assert len(ep_a.pending_output) == 100
    # now let b receive: it gets a clean prefix starting at frame 0
    got = []
    for _ in range(100):
        evs = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=1, advance_ms=250)
        got.extend(e for e in evs[id(ep_b)] if isinstance(e, EvInput))
        if got and got[-1].input.frame == 99:
            break
    frames = sorted({e.input.frame for e in got})
    assert frames == list(range(100))  # everything eventually arrives
