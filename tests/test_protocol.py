"""PeerEndpoint state machine in isolation over the fault-injecting virtual
network — the protocol-level coverage the reference lacks (SURVEY.md §4)."""

import random

from ggrs_tpu.frame_info import PlayerInput
from ggrs_tpu.network.protocol import (
    NUM_SYNC_PACKETS,
    EvDisconnected,
    EvInput,
    EvNetworkInterrupted,
    EvNetworkResumed,
    EvSynchronized,
    PeerEndpoint,
)
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.sync_layer import ConnectionStatus
from ggrs_tpu.utils.clock import FakeClock


def make_pair(clock, net, **net_kwargs):
    sock_a = net.socket("a")
    sock_b = net.socket("b")
    kwargs = dict(
        num_players=2,
        local_players=1,
        max_prediction=8,
        disconnect_timeout_ms=2000,
        disconnect_notify_start_ms=500,
        fps=60,
        input_size=1,
        clock=clock,
    )
    ep_a = PeerEndpoint(handles=[1], peer_addr="b", rng=random.Random(1), **kwargs)
    ep_b = PeerEndpoint(handles=[0], peer_addr="a", rng=random.Random(2), **kwargs)
    return (ep_a, sock_a), (ep_b, sock_b)


def pump(pairs, status, clock, steps=1, advance_ms=10):
    events = {id(ep): [] for ep, _ in pairs}
    for _ in range(steps):
        for ep, sock in pairs:
            for _, msg in sock.receive_all_messages():
                ep.handle_message(msg)
            events[id(ep)].extend(ep.poll(status))
            ep.send_all_messages(sock)
        clock.advance(advance_ms)
    return events


def test_sync_handshake_completes():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair(clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    events = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2 * NUM_SYNC_PACKETS)
    assert ep_a.is_running() and ep_b.is_running()
    assert any(isinstance(e, EvSynchronized) for e in events[id(ep_a)])
    assert any(isinstance(e, EvSynchronized) for e in events[id(ep_b)])


def test_sync_survives_heavy_loss():
    clock = FakeClock()
    net = InMemoryNetwork(clock, loss=0.5, seed=99)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair(clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    # retries happen on the 200ms sync timer; give it simulated seconds
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=200, advance_ms=50)
    assert ep_a.is_running() and ep_b.is_running()


def _sync(clock, net):
    pair = make_pair(clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    pair[0][0].synchronize()
    pair[1][0].synchronize()
    for _ in range(100):
        pump(list(pair), status, clock, steps=1, advance_ms=60)
        if pair[0][0].is_running() and pair[1][0].is_running():
            break
    assert pair[0][0].is_running() and pair[1][0].is_running()
    return pair, status


def test_input_transmission_under_loss_recovers_by_resend():
    clock = FakeClock()
    net = InMemoryNetwork(clock, loss=0.4, seed=7)
    ((ep_a, sock_a), (ep_b, sock_b)), status = _sync(clock, net)

    sent = []
    got = []
    for frame in range(30):
        inp = PlayerInput(frame, bytes([frame % 11]))
        sent.append(inp.buf)
        ep_a.send_input({1: inp}, status)
        evs = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2, advance_ms=120)
        got.extend(e for e in evs[id(ep_b)] if isinstance(e, EvInput))
    # tail resends: keep pumping until everything arrived
    for _ in range(50):
        evs = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=1, advance_ms=120)
        got.extend(e for e in evs[id(ep_b)] if isinstance(e, EvInput))
        if len(got) == 30:
            break

    assert [e.input.frame for e in got] == list(range(30))  # in order, no gaps
    assert [e.input.buf for e in got] == sent
    # ep_b's endpoint represents remote player 0; inputs attribute to it
    assert all(e.player == 0 for e in got)


def test_rtt_estimation():
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=40)
    ((ep_a, sock_a), (ep_b, sock_b)), status = _sync(clock, net)
    # quality reports fire on their 200ms timer; replies echo the ping time
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=20, advance_ms=50)
    assert 40 <= ep_a.round_trip_time <= 200


def test_interrupt_resume_and_disconnect():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    ((ep_a, sock_a), (ep_b, sock_b)), status = _sync(clock, net)

    # silence from b: a must emit NetworkInterrupted after 500ms
    evs_a = []
    for _ in range(8):
        for _, msg in sock_a.receive_all_messages():
            pass  # drop everything b might have queued earlier
        evs_a.extend(ep_a.poll(status))
        clock.advance(100)
    assert any(isinstance(e, EvNetworkInterrupted) for e in evs_a)
    assert not any(isinstance(e, EvDisconnected) for e in evs_a)

    # traffic resumes: NetworkResumed
    ep_b.send_input({0: PlayerInput(0, b"\x01")}, status)
    ep_b.send_all_messages(sock_b)
    evs = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=1)
    assert any(isinstance(e, EvNetworkResumed) for e in evs[id(ep_a)])

    # then full silence past the 2000ms timeout: Disconnected
    evs_a = []
    for _ in range(25):
        sock_a.receive_all_messages()
        evs_a.extend(ep_a.poll(status))
        clock.advance(100)
    assert any(isinstance(e, EvDisconnected) for e in evs_a)


def test_keep_alive_prevents_disconnect():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    pair, status = _sync(clock, net)
    # no game inputs at all, only timers: keep-alives must keep both sides up
    evs = pump(list(pair), status, clock, steps=100, advance_ms=100)
    for ep, _ in pair:
        assert ep.is_running()
        assert not any(isinstance(e, EvDisconnected) for e in evs[id(ep)])


def test_magic_filter_rejects_forged_packets():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    ((ep_a, sock_a), (ep_b, sock_b)), status = _sync(clock, net)
    from ggrs_tpu.network.messages import InputAck, Message

    before = ep_a.pending_output.copy()
    ep_a.send_input({1: PlayerInput(0, b"\x05")}, status)
    assert len(ep_a.pending_output) == 1
    # forged ack with a wrong magic must be ignored
    ep_a.handle_message(Message(magic=ep_b.magic ^ 0x5555, body=InputAck(ack_frame=5)))
    assert len(ep_a.pending_output) == 1


def test_oversized_pending_window_sends_prefix_instead_of_crashing():
    """A long un-acked window of incompressible inputs must not kill the
    session: the endpoint sends the longest prefix fitting the UDP budget."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    sock_a = net.socket("a")
    sock_b = net.socket("b")
    kwargs = dict(
        num_players=2,
        local_players=2,
        max_prediction=8,
        disconnect_timeout_ms=2000,
        disconnect_notify_start_ms=500,
        fps=60,
        input_size=8,  # 16 bytes/frame across both local players
        clock=clock,
    )
    ep_a = PeerEndpoint(handles=[0, 1], peer_addr="b", rng=random.Random(3), **kwargs)
    ep_b = PeerEndpoint(handles=[0, 1], peer_addr="a", rng=random.Random(4), **kwargs)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=12)
    assert ep_a.is_running()

    rng = random.Random(9)
    # b never acks (we just don't pump it); push 100 incompressible frames
    for frame in range(100):
        buf = bytes(rng.randrange(256) for _ in range(8))
        ep_a.send_input(
            {0: PlayerInput(frame, buf), 1: PlayerInput(frame, buf)}, status
        )
    ep_a.send_all_messages(sock_a)  # must not raise
    assert len(ep_a.pending_output) == 100
    # now let b receive: it gets a clean prefix starting at frame 0
    got = []
    for _ in range(100):
        evs = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=1, advance_ms=250)
        got.extend(e for e in evs[id(ep_b)] if isinstance(e, EvInput))
        if got and got[-1].input.frame == 99:
            break
    frames = sorted({e.input.frame for e in got})
    assert frames == list(range(100))  # everything eventually arrives


# ---------------------------------------------------------------------------
# network_stats: kbps math, window age, recv/loss/jitter estimators
# ---------------------------------------------------------------------------


def test_network_stats_window_too_young_is_distinguishable():
    """Before the first full second of the stats window the endpoint raises
    StatsWindowTooYoung — a NotSynchronized subclass, so catch-all callers
    keep working, but the two conditions are tellable apart."""
    import pytest

    from ggrs_tpu.errors import NotSynchronized, StatsWindowTooYoung

    clock = FakeClock()
    net = InMemoryNetwork(clock)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair(clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    # not even synchronizing yet: the plain NotSynchronized, not the subclass
    with pytest.raises(NotSynchronized) as exc:
        ep_a.network_stats()
    assert not isinstance(exc.value, StatsWindowTooYoung)
    ep_a.synchronize()
    ep_b.synchronize()
    # mid-handshake the truthful error stays the plain NotSynchronized,
    # even though the stats window is also young
    clock.advance(500)
    with pytest.raises(NotSynchronized) as exc:
        ep_a.network_stats()
    assert not isinstance(exc.value, StatsWindowTooYoung)
    # finish the handshake fast (well under the 1s window age)
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2 * NUM_SYNC_PACKETS, advance_ms=10)
    assert ep_a.is_running()
    assert clock.now_ms() - ep_a.stats_start_time < 1000
    with pytest.raises(StatsWindowTooYoung):
        ep_a.network_stats()
    clock.advance(1000)
    stats = ep_a.network_stats()  # window aged past 1s: rates reportable
    assert stats.kbps_sent >= 0


def test_network_stats_kbps_math_sent_and_recv():
    from ggrs_tpu.network.protocol import UDP_HEADER_SIZE

    clock = FakeClock()
    net = InMemoryNetwork(clock)
    ((ep_a, sock_a), (ep_b, sock_b)), status = _sync(clock, net)

    for frame in range(50):
        ep_a.send_input({1: PlayerInput(frame, bytes([frame % 11]))}, status)
        pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=1, advance_ms=50)

    window_s = (clock.now_ms() - ep_a.stats_start_time) // 1000
    assert window_s >= 1
    stats = ep_a.network_stats()
    expected_sent = (
        (ep_a.bytes_sent + ep_a.packets_sent * UDP_HEADER_SIZE) // window_s
    ) // 1024
    expected_recv = (
        (ep_a.bytes_recv + ep_a.packets_recv * UDP_HEADER_SIZE) // window_s
    ) // 1024
    assert stats.kbps_sent == expected_sent
    assert stats.kbps_recv == expected_recv
    # traffic flowed both ways during the pumps
    assert ep_a.bytes_recv > 0 and ep_a.packets_recv > 0


def test_recv_counters_track_delivered_wire_bytes():
    from ggrs_tpu.network.messages import encode_message

    clock = FakeClock()
    net = InMemoryNetwork(clock)
    ((ep_a, sock_a), (ep_b, sock_b)), status = _sync(clock, net)

    base_packets, base_bytes = ep_b.packets_recv, ep_b.bytes_recv
    ep_a.send_input({1: PlayerInput(0, b"\x09")}, status)
    ep_a.send_all_messages(sock_a)
    delivered = sock_b.receive_all_messages()
    assert delivered
    wire_total = sum(len(encode_message(m)) for _, m in delivered)
    for _, msg in delivered:
        ep_b.handle_message(msg)
    assert ep_b.packets_recv - base_packets == len(delivered)
    assert ep_b.bytes_recv - base_bytes == wire_total


def test_packet_loss_estimated_from_quality_report_gaps():
    """Quality reports fire on a fixed 200ms cadence carrying the sender's
    clock; dropping every other one must show up as packets_lost on the
    receiver without any wire-format change."""
    from ggrs_tpu.network.messages import QualityReport

    clock = FakeClock()
    net = InMemoryNetwork(clock)
    ((ep_a, sock_a), (ep_b, sock_b)), status = _sync(clock, net)
    assert ep_a.packets_lost == 0

    dropped = kept = 0
    for i in range(20):
        clock.advance(250)  # past the 200ms quality-report timer
        ep_b.poll(status)
        ep_b.send_all_messages(sock_b)
        for _, msg in sock_a.receive_all_messages():
            if isinstance(msg.body, QualityReport):
                if i % 2 == 0:
                    dropped += 1
                    continue  # simulate datagram loss
                kept += 1
            ep_a.handle_message(msg)
        ep_a.poll(status)
        ep_a.send_all_messages(sock_a)
        # let b consume replies so its timers stay honest
        for _, msg in sock_b.receive_all_messages():
            ep_b.handle_message(msg)
    assert dropped > 0 and kept > 0
    # each kept report following a dropped one shows a 2-interval gap
    assert ep_a.packets_lost >= kept - 1
    assert ep_a.network_stats().packets_lost == ep_a.packets_lost


def test_jitter_tracks_rtt_variation():
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=10)
    ((ep_a, sock_a), (ep_b, sock_b)), status = _sync(clock, net)
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=20, advance_ms=50)
    settled = ep_a.jitter_ms
    # now swing the latency hard: jitter must rise above the settled level
    net.latency_ms = 150
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=10, advance_ms=60)
    net.latency_ms = 10
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=10, advance_ms=60)
    assert ep_a.jitter_ms > settled
    assert ep_a.network_stats().jitter_ms == int(round(ep_a.jitter_ms))
