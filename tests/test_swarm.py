"""Third model family (swarm: 3D drones, [N,3] state vectors, battery
economy) — the adapter-contract witness (VERDICT r2 item 7): a new game
costs one PlaneAdapter, not a kernel rewrite. Covers device-vs-oracle
ground truth, full-carry parity across ALL THREE kernels (whole-batch
pallas, entity-tiled, sharded tiled), divergence detection, and beam
adoption on the new family."""

import numpy as np
import pytest

import jax
import jax.tree_util as jtu

from ggrs_tpu.models.swarm import (
    Swarm,
    checksum_oracle,
    init_oracle,
    step_oracle,
)
from ggrs_tpu.tpu import TpuSyncTestSession

P = 2


def drive(game, backend, script, check_distance, batches=3, **kw):
    sess = TpuSyncTestSession(
        game,
        num_players=P,
        check_distance=check_distance,
        backend=backend,
        **kw,
    )
    t = script.shape[0] // batches
    for i in range(batches):
        sess.advance_frames(script[i * t : (i + 1) * t])
    return sess


def assert_carry_equal(a, b):
    la = jtu.tree_leaves_with_path(jax.device_get(a))
    lb = jtu.tree_leaves(jax.device_get(b))
    assert len(la) == len(lb)
    for (path, x), y in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=jtu.keystr(path)
        )


def test_swarm_device_matches_oracle():
    """Straight replay: the jax step tracks the numpy oracle bit-for-bit,
    boost/battery dynamics included."""
    game = Swarm(P, 256)
    state = game.init_state()
    oracle = init_oracle(P, 256)
    rng = np.random.default_rng(21)
    statuses = np.zeros((P,), dtype=np.int32)
    for f in range(60):
        inputs = rng.integers(0, 128, size=(P, 1), dtype=np.uint8)
        state = game.step(state, inputs, statuses)
        oracle = step_oracle(oracle, inputs, statuses, P)
    dev = jax.device_get(state)
    for k in ("frame", "pos", "vel", "charge"):
        np.testing.assert_array_equal(np.asarray(dev[k]), oracle[k], err_msg=k)
    hi, lo = jax.device_get(game.checksum(state))
    ohi, olo = checksum_oracle(oracle)
    assert (int(hi), int(lo)) == (ohi, olo)


def test_swarm_battery_is_live():
    """BOOST doubles acceleration while charge lasts and drains it — the
    economy actually gates the dynamics (not a dead plane)."""
    statuses = np.zeros((P,), dtype=np.int32)
    plain, boosted = init_oracle(P, 64), init_oracle(P, 64)
    from ggrs_tpu.models.swarm import INPUT_BOOST, INPUT_XP

    for _ in range(40):
        plain = step_oracle(
            plain, np.full((P, 1), INPUT_XP, np.uint8), statuses, P
        )
        boosted = step_oracle(
            boosted, np.full((P, 1), INPUT_XP | INPUT_BOOST, np.uint8),
            statuses, P,
        )
    assert not np.array_equal(plain["pos"], boosted["pos"])
    assert (boosted["charge"] < plain["charge"]).all()


@pytest.mark.parametrize(
    "backend", ["pallas-interpret", "pallas-tiled-interpret"]
)
def test_swarm_kernel_carry_parity_with_xla(backend):
    """The contract payoff: the SAME generic kernels run the new family's
    [N,3] planes with full-carry bit parity vs the XLA scan."""
    rng = np.random.default_rng(22)
    script = rng.integers(0, 128, size=(36, P, 1), dtype=np.uint8)
    xla = drive(Swarm(P, 1024), "xla", script, check_distance=4)
    ker = drive(Swarm(P, 1024), backend, script, check_distance=4)
    assert_carry_equal(xla.carry, ker.carry)
    ker.check()


def test_swarm_sharded_tiled_parity():
    """And the sharded composition: one tiled kernel per device over the
    entity axis, psum'd checksums — same carry, third family."""
    from ggrs_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    rng = np.random.default_rng(23)
    script = rng.integers(0, 128, size=(24, P, 1), dtype=np.uint8)
    plain = drive(Swarm(P, 2048), "pallas-tiled-interpret", script, 4)
    sharded = drive(
        Swarm(P, 2048), "pallas-tiled-interpret", script, 4, mesh=mesh
    )
    assert_carry_equal(plain.carry, sharded.carry)
    sharded.check()


def test_swarm_pallas_detects_injected_divergence():
    from ggrs_tpu.errors import MismatchedChecksum

    rng = np.random.default_rng(24)
    script = rng.integers(0, 128, size=(30, P, 1), dtype=np.uint8)
    sess = TpuSyncTestSession(
        Swarm(P, 256), num_players=P, check_distance=4,
        backend="pallas-interpret",
    )
    sess.advance_frames(script[:15])
    sess.check()
    ring = dict(sess.carry["ring"])
    slot = (sess.current_frame - 4) % sess.ring_len
    ring["charge"] = ring["charge"].at[slot, 0].add(1)
    sess.carry = {**sess.carry, "ring": ring}
    sess.advance_frames(script[15:])
    with pytest.raises(MismatchedChecksum):
        sess.check()


def test_swarm_beam_adoption_matches_plain():
    """Beam speculation generalizes to the third family (declared statuses
    contract): constant inputs adopt, states bit-match a plain backend."""
    from ggrs_tpu import SessionBuilder
    from ggrs_tpu.tpu import TpuRollbackBackend

    def make_backend(bw):
        return TpuRollbackBackend(
            Swarm(P, 64), max_prediction=6, num_players=P, beam_width=bw
        )

    def make_sess():
        return (
            SessionBuilder(input_size=1)
            .with_num_players(P)
            .with_max_prediction_window(6)
            .with_check_distance(3)
            .start_synctest_session()
        )

    beam, plain = make_backend(8), make_backend(0)
    sb, sp = make_sess(), make_sess()
    for t in range(30):
        for h in range(P):
            sb.add_local_input(h, bytes([5 + h]))
            sp.add_local_input(h, bytes([5 + h]))
        beam.handle_requests(sb.advance_frame())
        plain.handle_requests(sp.advance_frame())
    a, b = beam.state_numpy(), plain.state_numpy()
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=k)
    assert beam.beam_hits > 0
