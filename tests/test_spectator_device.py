"""SpectatorSession feeding the device backend (VERDICT r1 item 5).

Spectators emit AdvanceFrame-only request streams — no Save, no Load
(src/sessions/p2p_spectator_session.rs:109-138) — including multi-frame
catch-up bursts. The TpuRollbackBackend must fulfill those streams
bit-identically to a host-fulfilled spectator replaying the same confirmed
inputs."""

import random

import numpy as np
import pytest

from ggrs_tpu import (
    PlayerType,
    PredictionThreshold,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.models import ex_game
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.tpu import TpuRollbackBackend
from ggrs_tpu.utils.clock import FakeClock

PLAYERS = 2
ENTITIES = 128


def build_mesh(clock, net, *, catchup_speed=1, max_frames_behind=10,
               native_spectator=False):
    """2-player host pair + one spectator watching host `a`."""

    def host(my_addr, other_addr, handle, spectator=None):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(PLAYERS)
            .with_max_prediction_window(8)
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
            .add_player(PlayerType.local(), handle)
            .add_player(PlayerType.remote(other_addr), 1 - handle)
        )
        if spectator:
            b = b.add_player(PlayerType.spectator(spectator), PLAYERS + 0)
        return b.start_p2p_session(net.socket(my_addr))

    sa = host("a", "b", 0, spectator="spec")
    sb = host("b", "a", 1)
    b = (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_clock(clock)
        .with_rng(random.Random(77))
        .with_max_frames_behind(max_frames_behind)
        .with_catchup_speed(catchup_speed)
    )
    if native_spectator:
        b = b.with_native_sessions(True)
    spec = b.start_spectator_session("a", net.socket("spec"))
    return sa, sb, spec


def sync_all(sessions, clock):
    for _ in range(400):
        for s in sessions:
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(s.current_state() == SessionState.RUNNING for s in sessions):
            return
    raise AssertionError("mesh failed to synchronize")


class HostStub:
    """Reference fulfiller: replays requests with the numpy oracle."""

    def __init__(self):
        self.state = ex_game.init_oracle(PLAYERS, ENTITIES)

    def handle_requests(self, requests):
        from ggrs_tpu import AdvanceFrame, LoadGameState, SaveGameState

        for req in requests:
            if isinstance(req, SaveGameState):
                req.cell.save(req.frame, {k: np.copy(v) for k, v in self.state.items()}, None)
            elif isinstance(req, LoadGameState):
                self.state = {k: np.copy(v) for k, v in req.cell.load().items()}
            elif isinstance(req, AdvanceFrame):
                inputs = np.array([b[0] for b, _ in req.inputs], dtype=np.uint8)
                statuses = np.array([int(s) for _, s in req.inputs], dtype=np.int32)
                self.state = ex_game.step_oracle(self.state, inputs, statuses, PLAYERS)


def drive(native_spectator=False, catchup_speed=1, stall_until=0,
          frames=40):
    """Run the mesh; the spectator's requests feed BOTH a device backend
    and the host oracle; returns (device_backend, oracle, spectator)."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    sa, sb, spec = build_mesh(
        clock, net, catchup_speed=catchup_speed,
        native_spectator=native_spectator,
    )
    sync_all([sa, sb, spec], clock)

    game_a, game_b = HostStub(), HostStub()
    device = TpuRollbackBackend(
        ex_game.ExGame(PLAYERS, ENTITIES), max_prediction=8, num_players=PLAYERS
    )
    oracle = HostStub()
    burst_sizes = []
    for frame in range(frames):
        sa.poll_remote_clients()
        sa.events()
        sa.add_local_input(0, bytes([(frame * 3 + 1) % 16]))
        game_a.handle_requests(sa.advance_frame())
        sb.poll_remote_clients()
        sb.events()
        sb.add_local_input(1, bytes([(frame * 5 + 2) % 16]))
        game_b.handle_requests(sb.advance_frame())
        spec.poll_remote_clients()
        spec.events()
        if frame >= stall_until:
            try:
                reqs = spec.advance_frame()
            except PredictionThreshold:
                reqs = []
            if reqs:
                burst_sizes.append(len(reqs))
                device.handle_requests(reqs)
                oracle.handle_requests(reqs)
        clock.advance(16)
    # drain whatever confirmed inputs remain
    for _ in range(30):
        spec.poll_remote_clients()
        try:
            reqs = spec.advance_frame()
        except PredictionThreshold:
            break
        burst_sizes.append(len(reqs))
        device.handle_requests(reqs)
        oracle.handle_requests(reqs)
        clock.advance(16)
    return device, oracle, spec, burst_sizes


def assert_state_equal(dev_state, oracle_state):
    for k in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(
            np.asarray(dev_state[k]), oracle_state[k], err_msg=k
        )


def test_spectator_device_backend_matches_oracle():
    device, oracle, spec, _ = drive()
    assert int(np.asarray(device.state_numpy()["frame"])) > 20
    assert_state_equal(device.state_numpy(), oracle.state)


def test_spectator_device_backend_catchup_bursts():
    """Stall the spectator, then let catch-up emit multi-AdvanceFrame
    ticks: the backend must fuse each burst into one dispatch and stay
    bit-identical to the host-fulfilled replica."""
    device, oracle, spec, bursts = drive(catchup_speed=3, stall_until=20)
    assert any(b >= 3 for b in bursts), f"no catch-up burst seen: {bursts}"
    assert_state_equal(device.state_numpy(), oracle.state)


def test_native_spectator_device_backend():
    from ggrs_tpu.native import available

    if not available():
        pytest.skip("native core not built")
    device, oracle, spec, _ = drive(native_spectator=True)
    assert int(np.asarray(device.state_numpy()["frame"])) > 20
    assert_state_equal(device.state_numpy(), oracle.state)
