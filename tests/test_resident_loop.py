"""Device-resident serving loop: the donated input mailbox
(tpu/mailbox.py) + the lax.while_loop virtual-tick driver
(MultiSessionDeviceCore._driver_impl) behind SessionHost(resident=True).

The correctness contract is the repo's usual bitwise one: a resident
host must be a BIT-EXACT replica of its dispatch-per-tick twin fed the
same seeded traffic — every session's checksum history, the canonical
stacked state AND ring bytes — across rollbacks (lossy network),
disconnects, starved lanes (speculation drafting in the holes) and
desync-report ordering, on the single-device core and the 8-shard
session mesh; the jit cache freezes after warmup under GGRS_SANITIZE=1;
and migration / checkpoint→restore drain the mailbox back to canonical
form so a session leaves resident mode bit-exactly."""

import random

import numpy as np
import pytest

import jax

from ggrs_tpu import PlayerType, SessionBuilder, SessionState
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.parallel.mesh import make_session_mesh
from ggrs_tpu.serve import SessionHost, migrate_session
from ggrs_tpu.tpu.backend import MultiSessionDeviceCore
from ggrs_tpu.types import DesyncDetection
from ggrs_tpu.utils.clock import FakeClock

ENTITIES = 16
FRAME_MS = 16


def _assert_tree_equal(ta, tb, what):
    la = jax.tree_util.tree_leaves_with_path(ta)
    lb = jax.tree_util.tree_leaves(tb)
    assert len(la) == len(lb)
    for (path, a), b in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"{what}{jax.tree_util.keystr(path)}",
        )


def build_fleet(*, resident, mesh=None, seed=13, sessions=16, ticks=40,
                loss=0.03, resident_ticks=8, on_tick=None,
                scripts_fn=None, **host_kw):
    """A seeded lossy loadgen fleet; `resident` picks the arm. Ample
    inflight window so the twin never throttles on backpressure (the
    resident arm has no dispatch queue — scheduling, and therefore
    traffic, must be identical across the arms)."""
    from ggrs_tpu.serve.loadgen import (
        build_matches,
        drive_scripted,
        make_scripts,
        sync_fleet,
    )

    clock = FakeClock()
    net = InMemoryNetwork(
        clock, latency_ms=20, jitter_ms=8, loss=loss, seed=seed
    )
    host = SessionHost(
        ExGame(num_players=4, num_entities=ENTITIES),
        max_prediction=8, num_players=4, max_sessions=sessions + 4,
        clock=clock, idle_timeout_ms=0, mesh=mesh,
        resident=resident, resident_ticks=resident_ticks,
        max_inflight_rows=4 * (sessions + 4), **host_kw,
    )
    matches = build_matches(host, net, clock, sessions=sessions, seed=seed)
    sync_fleet(host, matches, clock)
    scripts = (
        scripts_fn(matches, ticks, seed)
        if scripts_fn is not None
        else make_scripts(matches, ticks, seed=seed)
    )
    desyncs = drive_scripted(
        host, matches, clock, scripts, ticks,
        on_tick=on_tick(net, matches) if on_tick is not None else None,
    )
    assert not desyncs, f"fleet desynced (resident={resident})"
    host.device.block_until_ready()
    return host, [k for keys in matches for k in keys]


def assert_bitwise_twins(host_r, keys_r, host_t, keys_t):
    """The parity core: per-session frame counters + checksum histories,
    then the canonical stacked worlds byte-for-byte."""
    published = 0
    for ka, kb in zip(keys_r, keys_t):
        sa, sb = host_r.session(ka), host_t.session(kb)
        assert sa.current_frame == sb.current_frame > 0
        assert sa.local_checksum_history == sb.local_checksum_history
        published += len(getattr(sa, "local_checksum_history", ()))
    assert published > 0  # non-vacuous: desync detection really ran
    rr, sr = host_r.device.stacked_canonical()
    rt, st = host_t.device.stacked_canonical()
    _assert_tree_equal(rr, rt, "rings")
    _assert_tree_equal(sr, st, "states")
    hi_r, lo_r = host_r.device.checksum_slots()
    hi_t, lo_t = host_t.device.checksum_slots()
    np.testing.assert_array_equal(hi_r, hi_t)
    np.testing.assert_array_equal(lo_r, lo_t)


# ----------------------------------------------------------------------
# bitwise parity vs the dispatch-per-tick twin
# ----------------------------------------------------------------------


def test_resident_bitwise_parity_lossy_fleet():
    """Lossy 16-session fleet (rollbacks every few ticks): the resident
    host matches its dispatch-per-tick twin bit for bit, while actually
    amortizing dispatches (driver engaged, megabatch path idle)."""
    host_r, keys_r = build_fleet(resident=True)
    host_t, keys_t = build_fleet(resident=False)
    assert_bitwise_twins(host_r, keys_r, host_t, keys_t)
    dev = host_r.device
    assert dev.driver_dispatches > 0
    assert dev.vticks_executed / dev.driver_dispatches > 1
    assert dev.mailbox.overflows == 0
    assert dev.mailbox.pending_rows == 0
    # session rows never rode the megabatch queue path
    assert dev.megabatches < host_t.device.megabatches


def test_resident_parity_under_starvation_and_disconnect():
    """The hostile arm: hold-shaped scripts, blackhole windows past the
    prediction gate (starved lanes -> speculation drafts in-loop
    bubbles), then a mid-run hard disconnect of one peer per match
    (DISCONNECTED statuses in the staged rows). Still bit-identical,
    still zero dropped inputs."""
    from ggrs_tpu.serve.loadgen import held_scripts, starve_on_tick

    def hostile(net, matches):
        starve = starve_on_tick(net, matches, hole_every=20, hole_len=12)

        def on_tick(t):
            starve(t)
            if t == 44:
                # hard-disconnect peer 0 of every match: every session
                # holding it as a REMOTE player marks it disconnected at
                # the same tick in both arms
                for m, keys in enumerate(matches):
                    net.set_blackhole([(m, 0)], True)

        return on_tick

    kw = dict(
        loss=0.01, ticks=60, speculation=True, warmup=False,
        scripts_fn=held_scripts, on_tick=hostile, seed=7,
    )
    host_r, keys_r = build_fleet(resident=True, **kw)
    host_t, keys_t = build_fleet(resident=False, **kw)
    assert_bitwise_twins(host_r, keys_r, host_t, keys_t)
    # the starved lanes really drafted, and both arms adopted the same
    assert (
        host_r.frames_served_from_speculation
        == host_t.frames_served_from_speculation
    )
    assert host_r.device.mailbox.overflows == 0


@pytest.mark.parametrize("resident_ticks", [1, 3, 16])
def test_resident_parity_any_cadence(resident_ticks):
    """The drive cadence is a pure performance knob: depth-1 (drive
    every tick), an odd mid value and a depth past the desync interval
    all produce identical bytes."""
    host_r, keys_r = build_fleet(
        resident=True, resident_ticks=resident_ticks, ticks=24, seed=29
    )
    host_t, keys_t = build_fleet(resident=False, ticks=24, seed=29)
    assert_bitwise_twins(host_r, keys_r, host_t, keys_t)


def test_resident_sharded_parity():
    """The sharded resident host (mailbox slot axis on the 8-shard
    session mesh, driver GSPMD-partitioned) vs the single-device
    dispatch-per-tick twin: both dimensions cross-checked at once."""
    mesh = make_session_mesh(8)
    host_r, keys_r = build_fleet(resident=True, mesh=mesh, ticks=30)
    host_t, keys_t = build_fleet(resident=False, ticks=30)
    assert host_r.device.driver_dispatches > 0
    assert_bitwise_twins(host_r, keys_r, host_t, keys_t)


# ----------------------------------------------------------------------
# GGRS_SANITIZE: frozen jit cache after warmup
# ----------------------------------------------------------------------


def test_resident_jit_cache_frozen_after_warmup():
    """warmup() compiles the driver variants + commit buckets with the
    megabatch grid; the lossy resident serve afterwards compiles
    NOTHING, and every dispatch-function cache (driver + commit
    included) stays within dispatch_bucket_budget()."""
    from ggrs_tpu.analysis.sanitize import (
        install_sanitizer,
        uninstall_sanitizer,
    )

    san = install_sanitizer()
    try:
        host, keys = build_fleet(
            resident=True, sessions=6, ticks=25, warmup=True
        )
        assert not san.recompiles, (
            "post-warmup recompile on the resident host:\n"
            + "\n".join(e.render() for e in san.recompiles)
        )
        dev = host.device
        cache = sum(
            fn._cache_size() for fn in dev._budget_fns().values()
        )
        assert cache <= dev.dispatch_bucket_budget()
        assert dev.driver_dispatches > 0
    finally:
        uninstall_sanitizer()


# ----------------------------------------------------------------------
# leaving resident mode: migration + checkpoint/kill→restore
# ----------------------------------------------------------------------


def _peer(net, clock, addr, other, handle, seed):
    return (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_max_prediction_window(8)
        .with_input_delay(1)
        .with_desync_detection_mode(DesyncDetection.on(interval=10))
        .with_clock(clock)
        .with_rng(random.Random(seed * 131 + handle + 7))
        .add_player(PlayerType.local(), handle)
        .add_player(PlayerType.remote(other), 1 - handle)
        .start_p2p_session(net.socket(addr))
    )


def test_migration_out_of_resident_host_bitwise():
    """A peer migrates mid-match from a RESIDENT host to a
    dispatch-per-tick host: the export drains the mailbox first, so the
    handoff carries canonical bytes and the migrated session stays a
    bit-exact replica of an unmigrated twin match on the same scripts."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=20, jitter_ms=0, loss=0.0)
    h1 = SessionHost(
        ExGame(num_players=2, num_entities=ENTITIES), max_prediction=8,
        num_players=2, max_sessions=4, clock=clock, idle_timeout_ms=0,
        resident=True, resident_ticks=8,
    )
    h2 = SessionHost(
        ExGame(num_players=2, num_entities=ENTITIES), max_prediction=8,
        num_players=2, max_sessions=4, clock=clock, idle_timeout_ms=0,
    )
    a0 = _peer(net, clock, "a0", "a1", 0, seed=1)
    a1 = _peer(net, clock, "a1", "a0", 1, seed=2)
    b0 = _peer(net, clock, "b0", "b1", 0, seed=3)
    b1 = _peer(net, clock, "b1", "b0", 1, seed=4)
    ka0 = h1.attach(a0)
    h1.attach(a1)
    kb0 = h1.attach(b0)
    h1.attach(b1)
    for _ in range(600):
        h1.tick()
        h2.tick()
        clock.advance(FRAME_MS)
        if all(
            s.current_state() == SessionState.RUNNING
            for s in (a0, a1, b0, b1)
        ):
            break
    else:
        raise AssertionError("matches failed to synchronize")

    script = lambda h, t: (t * 3 + h * 5 + 1) % 16  # noqa: E731
    desyncs = []
    keymap = [(a0, h1, ka0, 0), (a1, h1, None, 1),
              (b0, h1, kb0, 0), (b1, h1, None, 1)]
    # recover the attach keys for a1/b1
    keymap[1] = (a1, h1, a1.host_key, 1)
    keymap[3] = (b1, h1, b1.host_key, 1)

    def drive(t):
        for sess, host, key, h in keymap:
            host.submit_input(key, h, bytes([script(h, t)]))
        for host in (h1, h2):
            for _k, evs in host.tick().items():
                desyncs.extend(
                    e for e in evs if type(e).__name__ == "DesyncDetected"
                )
        clock.advance(FRAME_MS)

    for t in range(24):
        drive(t)
    # the handoff happens with mailbox rows pending (mid fill cycle)
    new_ka0 = migrate_session(h1, h2, ka0)
    keymap[0] = (a0, h2, new_ka0, 0)
    for t in range(24, 90):
        drive(t)

    assert not desyncs, f"migration out of resident mode desynced: {desyncs[:3]}"
    assert a0.current_frame == b0.current_frame > 40
    common = set(a0.local_checksum_history) & set(b0.local_checksum_history)
    assert common
    for f in common:
        assert a0.local_checksum_history[f] == b0.local_checksum_history[f]
    migrated = h2.device.state_numpy(h2._lanes[new_ka0].slot)
    twin = h1.device.state_numpy(h1._lanes[kb0].slot)
    for k in migrated:
        np.testing.assert_array_equal(
            np.asarray(migrated[k]), np.asarray(twin[k]),
            err_msg=f"state[{k}]",
        )


def test_resident_checkpoint_restore_round_trip(tmp_path):
    """kill→restore out of resident mode: a resident host's checkpoint
    (mailbox drained to canonical form) restores onto a fresh
    NON-resident core bit-exactly — and matches the canonical bytes of
    the dispatch-per-tick twin fed the same traffic."""
    host_r, _ = build_fleet(resident=True, ticks=30, seed=21)
    host_t, _ = build_fleet(resident=False, ticks=30, seed=21)
    path = str(tmp_path / "resident.npz")
    host_r.checkpoint(path)
    restored = MultiSessionDeviceCore.restore(
        path, ExGame(num_players=4, num_entities=ENTITIES)
    )
    rr, sr = restored.stacked_canonical()
    rt, st = host_t.device.stacked_canonical()
    _assert_tree_equal(rr, rt, "rings")
    _assert_tree_equal(sr, st, "states")
