"""Delta+RLE codec (parity with src/network/compression.rs:63-91 plus
property tests of our RLE container)."""

import random

from ggrs_tpu.network import compression as comp


def test_encode_decode_identity():
    ref = bytes([0, 0, 0, 1])
    pending = [
        bytes([0, 0, 1, 0]),
        bytes([0, 0, 1, 1]),
        bytes([0, 1, 0, 0]),
        bytes([0, 1, 0, 1]),
        bytes([0, 1, 1, 0]),
    ]
    encoded = comp.encode(ref, pending)
    assert comp.decode(ref, encoded) == pending


def test_rle_roundtrip_cases():
    cases = [
        b"",
        b"\x00" * 100,
        b"\xff" * 100,
        b"abc",
        b"\x00\x00\x01\x00\x00\x00\xff\xff\xff\xff\x07",
        bytes(range(256)),
    ]
    for data in cases:
        assert comp.rle_decode(comp.rle_encode(data)) == data


def test_rle_roundtrip_random():
    rng = random.Random(42)
    for _ in range(200):
        n = rng.randrange(0, 300)
        # biased toward runs of 0x00/0xff, the shape real deltas have
        data = bytes(
            rng.choice([0, 0, 0, 0xFF, 0xFF, rng.randrange(256)]) for _ in range(n)
        )
        assert comp.rle_decode(comp.rle_encode(data)) == data


def test_identical_inputs_compress_tiny():
    ref = bytes(8)
    pending = [ref] * 64  # identical inputs -> one RLE run
    encoded = comp.encode(ref, pending)
    assert len(encoded) < 8
    assert comp.decode(ref, encoded) == pending
