"""Speculative input beam: vmapped rollouts must match per-candidate oracle
rollouts, and beam selection must shortcut the rollback."""

import numpy as np

from ggrs_tpu.models import ex_game


def test_beam_rollout_matches_oracle():
    import jax

    from ggrs_tpu.tpu.beam import BeamSpeculator

    players, entities, window, width = 2, 128, 8, 16
    game = ex_game.ExGame(players, entities)
    spec = BeamSpeculator(game, window=window, beam_width=width, num_players=players)

    state = game.init_state()
    host_state = ex_game.init_oracle(players, entities)

    rng = np.random.default_rng(11)
    beam_inputs = rng.integers(0, 16, size=(width, window, players, 1), dtype=np.uint8)
    beam_statuses = np.ones((width, window, players), dtype=np.int32)  # predicted

    finals, hi, lo = spec.rollout(state, beam_inputs, beam_statuses)

    for b in (0, 7, 15):
        s = {k: np.copy(v) for k, v in host_state.items()}
        for w in range(window):
            s = ex_game.step_oracle(s, beam_inputs[b, w], beam_statuses[b, w], players)
        ohi, olo = ex_game.checksum_oracle(s)
        assert int(hi[b]) == ohi and int(lo[b]) == olo

    picked = spec.select(finals, 7)
    got = jax.device_get(picked)
    s = {k: np.copy(v) for k, v in host_state.items()}
    for w in range(window):
        s = ex_game.step_oracle(s, beam_inputs[7, w], beam_statuses[7, w], players)
    for key in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(np.asarray(got[key]), s[key])


def test_candidate_generation_and_matching():
    from ggrs_tpu.tpu.beam import match_beam, repeat_last_beam

    last = np.array([[0b0101], [0b0010]], dtype=np.uint8)
    beam = repeat_last_beam(last, window=8, beam_width=16)
    assert beam.shape == (16, 8, 2, 1)
    # member 0 is the reference's repeat-last prediction
    assert np.all(beam[0] == np.tile(last, (8, 1, 1)))
    # all members are distinct futures
    flat = {beam[b].tobytes() for b in range(16)}
    assert len(flat) == 16

    # exact confirmed prefix picks the right member
    actual = np.tile(last, (5, 1, 1))
    assert match_beam(beam, actual) == 0
    # a future nobody speculated -> None
    wild = np.full((5, 2, 1), 0xAB, dtype=np.uint8)
    assert match_beam(beam, wild) is None
