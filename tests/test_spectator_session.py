"""Host + spectator over the virtual network
(parity with tests/test_p2p_spectator_session.rs plus catch-up coverage)."""

import random

import pytest

from ggrs_tpu import (
    NotSynchronized,
    PlayerType,
    PredictionThreshold,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.utils.clock import FakeClock
from stubs import GameStub


def build_host_and_spectator(clock, net, *, catchup_speed=1, max_frames_behind=10):
    host = (
        SessionBuilder(input_size=1)
        .with_num_players(1)
        .with_clock(clock)
        .with_rng(random.Random(21))
        .add_player(PlayerType.local(), 0)
        .add_player(PlayerType.spectator("spec"), 1)
        .start_p2p_session(net.socket("host"))
    )
    spec = (
        SessionBuilder(input_size=1)
        .with_num_players(1)
        .with_clock(clock)
        .with_rng(random.Random(22))
        .with_max_frames_behind(max_frames_behind)
        .with_catchup_speed(catchup_speed)
        .start_spectator_session("host", net.socket("spec"))
    )
    return host, spec


def sync_all(host, spec, clock):
    for _ in range(60):
        host.poll_remote_clients()
        spec.poll_remote_clients()
        host.events()
        spec.events()
        clock.advance(20)
        if (
            host.current_state() == SessionState.RUNNING
            and spec.current_state() == SessionState.RUNNING
        ):
            return
    raise AssertionError("host/spectator failed to synchronize")


def test_spectator_not_synchronized_initially():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    _host, spec = build_host_and_spectator(clock, net)
    with pytest.raises(NotSynchronized):
        spec.advance_frame()


def test_spectator_replays_host_inputs():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host, spec = build_host_and_spectator(clock, net)
    sync_all(host, spec, clock)

    hg, sg = GameStub(), GameStub()
    for frame in range(30):
        host.add_local_input(0, bytes([frame % 9]))
        hg.handle_requests(host.advance_frame())
        try:
            sg.handle_requests(spec.advance_frame())
        except PredictionThreshold:
            pass  # input not here yet; wait
        clock.advance(16)

    # let the spectator catch up on remaining confirmed inputs
    for _ in range(30):
        host.poll_remote_clients()
        try:
            sg.handle_requests(spec.advance_frame())
        except PredictionThreshold:
            break
        clock.advance(16)

    assert sg.gs.frame > 0
    # the spectator's replica is a prefix of the host's trajectory: replaying
    # the host's confirmed inputs yields the identical state machine
    ref = GameStub()
    host2, spec2 = sg.gs.frame, sg.gs.state
    assert hg.gs.frame >= sg.gs.frame
    # deterministic stub: same inputs => same state; spot-check via frames
    assert spec2 == _stub_state_at(frame_inputs=[(f % 9) for f in range(host2)])


def _stub_state_at(frame_inputs):
    g = GameStub()
    state = 0
    for b in frame_inputs:
        state += b + 1
    return state


def test_spectator_catchup_speed():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host, spec = build_host_and_spectator(clock, net, catchup_speed=2, max_frames_behind=5)
    sync_all(host, spec, clock)

    hg, sg = GameStub(), GameStub()
    # host runs ahead without the spectator advancing
    for frame in range(20):
        host.add_local_input(0, b"\x01")
        hg.handle_requests(host.advance_frame())
        spec.poll_remote_clients()
        clock.advance(16)

    assert spec.frames_behind_host() > 5
    # now the spectator advances 2 frames per call until caught up
    sg_frames = []
    for _ in range(20):
        try:
            reqs = spec.advance_frame()
        except PredictionThreshold:
            break
        sg.handle_requests(reqs)
        sg_frames.append(len(reqs))
    assert 2 in sg_frames  # catch-up kicked in


def test_spectator_waits_when_input_not_arrived():
    """PredictionThreshold when the host's input for the next frame hasn't
    arrived (src/sessions/p2p_spectator_session.rs:179-182); the spectator's
    frame must NOT advance, and the same frame replays once it arrives."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host, spec = build_host_and_spectator(clock, net)
    sync_all(host, spec, clock)

    stub_h = GameStub()
    # host advances a couple frames; spectator consumes them all
    for f in range(3):
        host.poll_remote_clients()
        host.add_local_input(0, bytes([f + 1]))
        stub_h.handle_requests(host.advance_frame())
        spec.poll_remote_clients()
        clock.advance(16)
    stub_s = GameStub()
    consumed = 0
    for _ in range(10):
        try:
            reqs = spec.advance_frame()
        except PredictionThreshold:
            break
        stub_s.handle_requests(reqs)
        consumed += len(reqs)
    assert consumed == 3
    before = spec.current_frame
    with pytest.raises(PredictionThreshold):
        spec.advance_frame()
    assert spec.current_frame == before  # no partial advance

    # host produces one more frame -> spectator resumes where it stopped
    host.poll_remote_clients()
    host.add_local_input(0, bytes([9]))
    stub_h.handle_requests(host.advance_frame())
    clock.advance(16)
    spec.poll_remote_clients()
    reqs = spec.advance_frame()
    stub_s.handle_requests(reqs)
    assert spec.current_frame == before + 1
    assert stub_s.history == stub_h.history


def test_spectator_too_far_behind_is_unrecoverable():
    """If the spectator stalls for > SPECTATOR_BUFFER_SIZE frames, the ring
    slot for its next frame has been overwritten by a newer frame
    (src/sessions/p2p_spectator_session.rs:184-187)."""
    from ggrs_tpu import SpectatorTooFarBehind
    from ggrs_tpu.sessions.builder import SPECTATOR_BUFFER_SIZE

    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host, spec = build_host_and_spectator(clock, net)
    sync_all(host, spec, clock)

    stub_h = GameStub()
    # host runs far ahead while the spectator never advances
    for f in range(SPECTATOR_BUFFER_SIZE + 10):
        host.poll_remote_clients()
        host.add_local_input(0, bytes([f % 7]))
        stub_h.handle_requests(host.advance_frame())
        spec.poll_remote_clients()
        clock.advance(16)
    with pytest.raises(SpectatorTooFarBehind):
        for _ in range(SPECTATOR_BUFFER_SIZE + 10):
            spec.advance_frame()
