"""Fleet operations: live session migration, HostGroup spillover /
kill→restore, mass-disconnect storms, and the WAN-chaos acceptance soak.

The parity discipline matches the serve suite: a migrated (or disturbed)
session must stay a BIT-EXACT replica of an undisturbed twin driven with
the same scripts — checksum histories agree frame-by-frame, and the live
device worlds compare equal byte-for-byte. Desync detection runs
throughout, so the zero-desync assertions are backed by real cross-peer
comparisons."""

import random

import numpy as np
import pytest

from ggrs_tpu import PlayerType, SessionBuilder, SessionState
from ggrs_tpu.errors import (
    CheckpointIncompatible,
    DrainStalled,
    GroupSaturated,
    HostFull,
    MigrationIncompatible,
)
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.obs import GLOBAL_TELEMETRY
from ggrs_tpu.serve import HostGroup, SessionHost, migrate_session
from ggrs_tpu.serve.migrate import export_session, import_session
from ggrs_tpu.types import DesyncDetection
from ggrs_tpu.utils.clock import FakeClock

ENTITIES = 16
FRAME_MS = 16


def make_host(clock, *, max_sessions=4, num_players=2, entities=ENTITIES,
              **kw):
    return SessionHost(
        ExGame(num_players=num_players, num_entities=entities),
        max_prediction=8,
        num_players=num_players,
        max_sessions=max_sessions,
        clock=clock,
        idle_timeout_ms=0,
        **kw,
    )


def peer(net, clock, addr, other, handle, *, seed=0, desync_interval=10,
         disconnect_timeout_ms=2000, sparse=False):
    """One half of a real 2-player P2P match over the virtual network."""
    return (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_max_prediction_window(8)
        .with_input_delay(1)
        .with_sparse_saving_mode(sparse)
        .with_desync_detection_mode(DesyncDetection.on(interval=desync_interval))
        .with_disconnect_timeout(disconnect_timeout_ms)
        .with_clock(clock)
        .with_rng(random.Random(seed * 131 + handle + 7))
        .add_player(PlayerType.local(), handle)
        .add_player(PlayerType.remote(other), 1 - handle)
        .start_p2p_session(net.socket(addr))
    )


def solo_session(net, addr, *, players=2):
    b = SessionBuilder(input_size=1).with_num_players(players)
    for h in range(players):
        b = b.add_player(PlayerType.local(), h)
    return b.start_p2p_session(net.socket(addr))


def sync_all(hosts, sessions, clock, max_ticks=600):
    for _ in range(max_ticks):
        for h in hosts:
            h.tick()
        clock.advance(FRAME_MS)
        if all(
            s.current_state() == SessionState.RUNNING for s in sessions
        ):
            return
    raise AssertionError("match failed to synchronize")


# ----------------------------------------------------------------------
# live migration: bitwise parity against an unmigrated twin
# ----------------------------------------------------------------------


def test_live_migration_bitwise_parity_vs_unmigrated_twin():
    """Two identical 2-player matches (same scripts) on host1; one peer
    of match A migrates to host2 mid-match. Peers keep exchanging
    checksums across the handoff (no resync, desync detection ON);
    afterwards the migrated session's world is BIT-IDENTICAL to the twin
    match's corresponding peer, and their published checksum histories
    agree frame by frame."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=20, jitter_ms=0, loss=0.0)
    h1, h2 = make_host(clock), make_host(clock)

    a0 = peer(net, clock, "a0", "a1", 0, seed=1)
    a1 = peer(net, clock, "a1", "a0", 1, seed=2)
    b0 = peer(net, clock, "b0", "b1", 0, seed=3)
    b1 = peer(net, clock, "b1", "b0", 1, seed=4)
    ka0, ka1 = h1.attach(a0), h1.attach(a1)
    kb0, kb1 = h1.attach(b0), h1.attach(b1)
    sync_all([h1, h2], [a0, a1, b0, b1], clock)

    script = lambda h, t: (t * 3 + h * 5 + 1) % 16  # same for A and B
    desyncs = []

    def drive(keymap, t):
        # keymap: session -> (host, key); twin peers share the script
        for sess, (host, key), h in keymap:
            host.submit_input(key, h, bytes([script(h, t)]))
        for host in (h1, h2):
            for key, evs in host.tick().items():
                desyncs.extend(
                    e for e in evs if type(e).__name__ == "DesyncDetected"
                )
        clock.advance(FRAME_MS)

    keymap = [
        (a0, (h1, ka0), 0), (a1, (h1, ka1), 1),
        (b0, (h1, kb0), 0), (b1, (h1, kb1), 1),
    ]
    for t in range(24):
        drive(keymap, t)

    # --- the handoff: a0 moves to h2 mid-match
    new_ka0 = migrate_session(h1, h2, ka0)
    assert a0.host_key == new_ka0 and a0._host is h2
    keymap[0] = (a0, (h2, new_ka0), 0)
    for t in range(24, 90):
        drive(keymap, t)

    assert not desyncs, f"migration caused desyncs: {desyncs[:3]}"
    # both matches ran the same scripts: frame counters agree...
    assert a0.current_frame == b0.current_frame > 40
    # ...checksum exchange kept running across the handoff (non-vacuous)
    assert len(a0.local_checksum_history) > 2
    common = set(a0.local_checksum_history) & set(b0.local_checksum_history)
    assert common, "twin matches published no comparable frames"
    for f in common:
        assert (
            a0.local_checksum_history[f] == b0.local_checksum_history[f]
        ), f"frame {f}: migrated session diverged from its twin"
    # ...and the live device worlds are bit-identical
    migrated = h2.device.state_numpy(h2._lanes[new_ka0].slot)
    twin = h1.device.state_numpy(h1._lanes[kb0].slot)
    for k in migrated:
        np.testing.assert_array_equal(
            np.asarray(migrated[k]), np.asarray(twin[k]),
            err_msg=f"state[{k}]",
        )


def test_migration_rejects_incompatible_destination_and_rolls_back():
    """A destination running a different game config must refuse the
    ticket with the typed MigrationIncompatible — and the one-call
    migrate_session rolls the session back onto the source, so a failed
    migration degrades to 'nothing happened'."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    src = make_host(clock)
    wrong = make_host(clock, entities=ENTITIES * 2)  # different world shape
    sess = solo_session(net, "m")
    key = src.attach(sess)
    for t in range(4):
        for h in (0, 1):
            src.submit_input(key, h, bytes([t % 16]))
        src.tick()
        clock.advance(FRAME_MS)
    with pytest.raises(MigrationIncompatible):
        migrate_session(src, wrong, key)
    # rolled back: still hosted on src, still advancing
    assert sess._host is src
    rolled_key = sess.host_key
    for h in (0, 1):
        src.submit_input(rolled_key, h, b"\x05")
    src.tick()
    assert src._lanes[rolled_key].current_frame == 5
    # a full destination raises HostFull from adopt, with the same rollback
    full = make_host(clock, max_sessions=1)
    full.attach(solo_session(net, "f"))
    with pytest.raises(HostFull):
        migrate_session(src, full, rolled_key)
    assert sess._host is src


def test_export_import_preserves_pending_inputs_and_frame():
    """A session exported BETWEEN submit and tick resumes on the new host
    with its pending-input bookkeeping intact: the first destination tick
    advances it, no input lost."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    h1, h2 = make_host(clock), make_host(clock)
    sess = solo_session(net, "p")
    key = h1.attach(sess)
    for h in (0, 1):
        h1.submit_input(key, h, b"\x07")  # submitted, NOT ticked
    ticket = export_session(h1, key)
    assert ticket.current_frame == 0
    assert ticket.pending_inputs == frozenset({0, 1})
    new_key = import_session(h2, ticket)
    h2.tick()
    assert h2._lanes[new_key].current_frame == 1


def test_migration_carries_input_model_stats():
    """Speculating hosts: the migration ticket carries the lane's
    learned input statistics by value (MigrationTicket.input_stats), so
    the destination resumes WARM — its draft model ranks switch
    candidates immediately, where a stats-dropped control restarts cold
    below MIN_HOLDS — and a starved post-handoff drive keeps the
    speculation hit rate positive with zero desyncs: prediction
    continuity across the handoff, not a relearn-from-scratch."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=10, jitter_ms=0, loss=0.0)
    h1 = make_host(clock, speculation=True)
    h2 = make_host(clock, speculation=True)
    h3 = make_host(clock, speculation=True)
    p0 = peer(net, clock, "i0", "i1", 0, seed=70)
    p1 = peer(net, clock, "i1", "i0", 1, seed=71)
    k0, k1 = h1.attach(p0), h1.attach(p1)
    sync_all([h1, h2, h3], [p0, p1], clock)

    desyncs = []

    def tick_all():
        for host in (h1, h2, h3):
            for _, evs in host.tick().items():
                desyncs.extend(
                    e for e in evs if type(e).__name__ == "DesyncDetected"
                )
        clock.advance(FRAME_MS)

    # 6-frame toggle holds: the shape the lane models learn from
    # finalized rows (frames beyond rollback reach)
    script = lambda h, t: (5 if (t // 6) % 2 == 0 else 9) + 4 * h
    for t in range(60):
        h1.submit_input(k0, 0, bytes([script(0, t)]))
        h1.submit_input(k1, 1, bytes([script(1, t)]))
        tick_all()

    # --- the handoff: the ticket carries the learned stats by value
    ticket = export_session(h1, k0)
    assert ticket.input_stats is not None
    assert ticket.input_stats["kind"] == "online"
    assert any(p["holds"] for p in ticket.input_stats["players"])
    new_k0 = import_session(h2, ticket)
    warm = h2.export_input_model_state(new_k0)
    assert warm == ticket.input_stats  # loaded, re-exported: identical
    warm_model = h2._spec._lanes[new_k0].model
    assert warm_model._stats[0].n_holds() >= warm_model.MIN_HOLDS
    # warm: ranks a switch candidate for the held value immediately
    probe = [(60, bytes([5]), 3), None]
    assert warm_model.rank_branches(probe, 60, 8, 6)

    # --- control: the same ticket with the stats dropped imports COLD
    ticket2 = export_session(h2, new_k0)
    stats2 = ticket2.input_stats
    ticket2.input_stats = None
    k3 = import_session(h3, ticket2)
    cold_model = h3._spec._lanes[k3].model
    assert cold_model._stats[0].n_holds() == 0
    assert cold_model.rank_branches(probe, 60, 8, 6) == []
    # restoring the dropped stats warms the lane back up
    assert h3.import_input_model_state(k3, stats2)
    assert h3._spec._lanes[k3].model._stats[0].n_holds() >= 3

    # --- hit-rate continuity: starve the migrated lane on its new home
    # (peer blackholed past the prediction window); held values make the
    # recovery a lineage full hit, so adoption must flow post-handoff
    for t in range(60, 130):
        if t == 70:
            net.set_blackhole({"i1"}, True)
        if t == 84:
            net.set_blackhole({"i1"}, False)
        h3.submit_input(k3, 0, bytes([5]))
        h1.submit_input(k1, 1, bytes([9]))
        tick_all()
    sec = h3._spec.section()
    assert sec["frames_adopted"] > 0 and sec["hit_rate"] > 0.0, sec
    assert not desyncs, f"handoff drive desynced: {desyncs[:3]}"
    assert p0.current_frame > 80 and p1.current_frame > 80


def test_sparse_saving_hosted_session_survives_wan_rtt():
    """Regression for the prediction-threshold gate under SPARSE SAVING:
    set_last_confirmed_frame clamps the watermark to last_saved_frame,
    but _check_last_saved_state repairs last_saved BEFORE the in-advance
    raise whenever the lag reaches the window — so the host's
    fresh-confirmed gate must keep sparse sessions advancing (never
    half-advancing into a PredictionThreshold raise, which the host
    would swallow while dropping the tick's save/rollback requests —
    silent divergence) even when RTT exceeds the prediction window."""
    clock = FakeClock()
    # ~200ms RTT = 12+ frames: every tick runs at the window edge
    net = InMemoryNetwork(clock, latency_ms=100, jitter_ms=0, loss=0.0)
    host = make_host(clock)
    p0 = peer(net, clock, "w0", "w1", 0, seed=60, sparse=True)
    p1 = peer(net, clock, "w1", "w0", 1, seed=61, sparse=True)
    k0, k1 = host.attach(p0), host.attach(p1)
    sync_all([host], [p0, p1], clock)
    desyncs = []
    for t in range(150):
        for key, h in ((k0, 0), (k1, 1)):
            host.submit_input(key, h, bytes([(t * 3 + h) % 16]))
        for _, evs in host.tick().items():
            desyncs.extend(
                e for e in evs if type(e).__name__ == "DesyncDetected"
            )
        clock.advance(FRAME_MS)
    assert not desyncs, f"sparse-saving WAN drive desynced: {desyncs[:3]}"
    # real progress at the window edge (RTT-bound, not wedged)...
    assert p0.current_frame > 60 and p1.current_frame > 60
    # ...and PredictionThreshold never leaked out of an advance (the
    # host records it as the lane's last_error when it does)
    for key in (k0, k1):
        assert host._lanes[key].last_error is None
    # the gate did real work: the session ran throttled at the edge
    assert host._lanes[k0].throttled_ticks > 0


# ----------------------------------------------------------------------
# HostGroup: spillover + bounded retry + typed saturation
# ----------------------------------------------------------------------


def test_hostgroup_spillover_and_typed_saturation():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    game = ExGame(num_players=2, num_entities=ENTITIES)
    group = HostGroup.build(
        game, 2, clock=clock, max_prediction=8, num_players=2,
        max_sessions=2, idle_timeout_ms=0, max_attempts=2, backoff_ms=16,
    )
    keys = [group.attach(solo_session(net, f"g{i}")) for i in range(4)]
    assert group.active_sessions == 4
    # load-balanced: both hosts carry sessions, and at least one attach
    # landed past a full first choice
    assert all(h.active_sessions == 2 for h in group.hosts)
    with pytest.raises(GroupSaturated) as exc_info:
        group.attach(solo_session(net, "overflow"))
    assert exc_info.value.attempts >= 2
    assert "host0" in exc_info.value.per_host
    assert group.saturations == 1
    # GroupSaturated IS a HostFull: catch-all admission handling works
    assert isinstance(exc_info.value, HostFull)
    # freeing capacity un-saturates the group
    host_idx = group.host_of(keys[0])
    group.hosts[host_idx].detach(group._records[keys[0]].hkey)
    group.tick()  # reconciles the detach into group bookkeeping
    group.attach(solo_session(net, "late"))
    assert group.active_sessions == 4


def test_hostgroup_drain_host_migrates_sessions_to_siblings():
    """Evicting a host from service routes its LIVE sessions through the
    migration handoff to siblings — then drains the empty host."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    game = ExGame(num_players=2, num_entities=ENTITIES)
    group = HostGroup.build(
        game, 2, clock=clock, max_prediction=8, num_players=2,
        max_sessions=4, idle_timeout_ms=0,
    )
    keys = [group.attach(solo_session(net, f"d{i}")) for i in range(4)]
    for t in range(6):
        for k in keys:
            for h in (0, 1):
                group.submit_input(k, h, bytes([t % 16]))
        group.tick()
        clock.advance(FRAME_MS)
    victim = group.host_of(keys[0])
    n_victim = len(group.keys_on(victim))
    group.drain_host(victim)
    assert victim in group.dead
    assert not group.keys_on(victim)
    assert group.migrations >= n_victim
    # migrated sessions keep advancing on their new homes
    for t in range(6, 10):
        for k in keys:
            for h in (0, 1):
                group.submit_input(k, h, bytes([t % 16]))
        group.tick()
        clock.advance(FRAME_MS)
    assert all(group.session(k).current_frame == 10 for k in keys)


# ----------------------------------------------------------------------
# mass-disconnect storm (satellite): GC accounting + survivor parity
# ----------------------------------------------------------------------


def test_mass_disconnect_storm_gc_and_survivor_parity():
    """Drop ALL peers of half the fleet in one tick (network blackhole —
    the peers never say goodbye). Disconnect GC must reclaim every
    stormed slot, the eviction counter must account exactly, and the
    surviving match must stay a bitwise replica of an undisturbed twin
    driven with the same scripts."""
    GLOBAL_TELEMETRY.enabled = True
    try:
        clock = FakeClock()
        net = InMemoryNetwork(clock, latency_ms=10, jitter_ms=0, loss=0.0)
        host = make_host(clock, max_sessions=8)

        # M0/M1: the storm victims. M2 (survivor) and M3 (twin) run the
        # same scripts as each other.
        # short disconnect timeout so the storm's GC resolves in tens of
        # ticks instead of the default 2s / 125 ticks (same machinery)
        m = {}
        for i in range(4):
            m[i] = (
                peer(net, clock, f"s{i}a", f"s{i}b", 0, seed=10 + i,
                     disconnect_timeout_ms=480),
                peer(net, clock, f"s{i}b", f"s{i}a", 1, seed=20 + i,
                     disconnect_timeout_ms=480),
            )
        keys = {
            i: (host.attach(m[i][0]), host.attach(m[i][1]))
            for i in range(4)
        }
        sync_all([host], [s for pair in m.values() for s in pair], clock)
        free_slots_running = len(host._free_slots)
        evicted_before = host.sessions_evicted

        script = lambda h, t: (t * 7 + h * 3 + 2) % 16
        desyncs = []

        def drive_tick(t, alive):
            for i in alive:
                for h, key in enumerate(keys[i]):
                    host.submit_input(key, h, bytes([script(h, t)]))
            for _, evs in host.tick().items():
                desyncs.extend(
                    e for e in evs if type(e).__name__ == "DesyncDetected"
                )
            clock.advance(FRAME_MS)

        for t in range(20):
            drive_tick(t, alive=(0, 1, 2, 3))
        # THE STORM: all four stormed peers go dark in one tick
        net.set_blackhole({"s0a", "s0b", "s1a", "s1b"})
        t = 20
        # disconnect timeout is 480ms -> ~30 ticks of 16ms; give slack
        while t < 100 and any(
            k in host._lanes for i in (0, 1) for k in keys[i]
        ):
            drive_tick(t, alive=(2, 3))
            t += 1

        # every stormed session was reclaimed by disconnect GC...
        for i in (0, 1):
            for k in keys[i]:
                assert k not in host.keys(), f"stormed session {k} undead"
        assert host.sessions_gced >= 4
        # ...the counter accounts exactly (4 evictions, all disconnect GC)
        assert host.sessions_evicted - evicted_before == 4
        snap = GLOBAL_TELEMETRY.registry.get(
            "ggrs_host_sessions_evicted_total"
        ).snapshot()
        assert snap["values"][""] == 4
        # ...their device slots are free again
        assert len(host._free_slots) == free_slots_running + 4
        # ...and the survivors kept bitwise parity with the twin
        assert not desyncs, f"storm desynced the survivors: {desyncs[:3]}"
        s2, s3 = m[2][0], m[3][0]
        assert s2.current_frame == s3.current_frame > 20
        common = set(s2.local_checksum_history) & set(
            s3.local_checksum_history
        )
        assert common
        for f in common:
            assert (
                s2.local_checksum_history[f] == s3.local_checksum_history[f]
            )
        a = host.device.state_numpy(host._lanes[keys[2][0]].slot)
        b = host.device.state_numpy(host._lanes[keys[3][0]].slot)
        for k in a:
            np.testing.assert_array_equal(
                np.asarray(a[k]), np.asarray(b[k]), err_msg=f"state[{k}]"
            )
    finally:
        GLOBAL_TELEMETRY.enabled = False
        GLOBAL_TELEMETRY.reset()


# ----------------------------------------------------------------------
# host kill -> restore-from-checkpoint
# ----------------------------------------------------------------------


def test_host_kill_restore_from_checkpoint(tmp_path):
    """Kill a host mid-match (emergency drain→checkpoint), let its
    sessions sit dark for a few ticks, restore a fresh host from the
    checkpoint file, and keep playing: zero desyncs, every session
    resumes at its exact frame, old slots reclaimed in place."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=10, jitter_ms=0, loss=0.0)
    game = ExGame(num_players=2, num_entities=ENTITIES)
    group = HostGroup.build(
        game, 2, clock=clock, max_prediction=8, num_players=2,
        max_sessions=4, idle_timeout_ms=0,
    )
    # a cross-host match: one peer on each host — the kill severs a live
    # protocol link, not just co-hosted twins
    p0 = peer(net, clock, "k0", "k1", 0, seed=40)
    p1 = peer(net, clock, "k1", "k0", 1, seed=41)
    g0, g1 = group.attach(p0), group.attach(p1)
    sync_all(group.hosts, [p0, p1], clock)

    desyncs = []

    def drive_tick(t):
        for g, h in ((g0, 0), (g1, 1)):
            group.submit_input(g, h, bytes([(t * 5 + h) % 16]))
        for _, evs in group.tick().items():
            desyncs.extend(
                e for e in evs if type(e).__name__ == "DesyncDetected"
            )
        clock.advance(FRAME_MS)

    for t in range(16):
        drive_tick(t)
    victim = group.host_of(g0)
    path = str(tmp_path / "kill.npz")
    frame_at_kill = p0.current_frame
    n = group.kill_host(victim, path)
    assert n == 1  # balanced attach put one peer on each host
    assert p0.host_key is None  # suspended, not pumped
    for t in range(16, 20):  # the blackout: inputs to the dead host drop
        drive_tick(t)
    assert group.inputs_dropped > 0
    resumed = group.restore_host(victim, path)
    assert resumed == n
    assert p0.host_key is not None
    # the restored lane resumes at the exact kill-time frame
    rec = group._records[g0]
    assert group.hosts[rec.host_idx]._lanes[rec.hkey].current_frame == (
        frame_at_kill
    )
    for t in range(20, 80):
        drive_tick(t)
    assert not desyncs, f"kill/restore desynced: {desyncs[:3]}"
    assert p0.current_frame > frame_at_kill + 40
    assert p1.current_frame > frame_at_kill + 40
    # real checksum comparisons backed the zero-desync claim
    assert len(p0.local_checksum_history) > 2

    # a checkpoint from a mismatched fleet is refused with the typed error
    wrong_group = HostGroup.build(
        game, 1, clock=clock, max_prediction=8, num_players=2,
        max_sessions=2, idle_timeout_ms=0,  # different capacity
    )
    wrong_group.dead.add(0)
    with pytest.raises(CheckpointIncompatible):
        wrong_group.restore_host(0, path)


# ----------------------------------------------------------------------
# DrainStalled: the typed flush-guard failure (satellite)
# ----------------------------------------------------------------------


def test_drain_stalled_is_typed_and_recorded():
    GLOBAL_TELEMETRY.enabled = True
    try:
        clock = FakeClock()
        net = InMemoryNetwork(clock)
        host = make_host(clock)
        key = host.attach(solo_session(net, "w"))
        for h in (0, 1):
            host.submit_input(key, h, b"\x01")
        # stage a row, then wedge the scheduler so it can never dispatch
        real_poll = host.device.poll_retired
        host.device.poll_retired = lambda: host.max_inflight_rows
        host.tick()
        assert host.queue_depth == 1
        with pytest.raises(DrainStalled) as exc_info:
            host._flush_ready("test", max_passes=50)
        err = exc_info.value
        assert err.queue_depth == 1
        assert err.passes == 50
        assert "queue_depth=1" in str(err)
        events = [
            e for e in GLOBAL_TELEMETRY.recorder.to_json()
            if e["kind"] == "host_drain_stalled"
        ]
        assert events and events[-1]["queue_depth"] == 1
        # un-wedged, the same drain flushes clean
        host.device.poll_retired = real_poll
        summary = host.drain()
        assert summary["queue_depth"] == 0
    finally:
        GLOBAL_TELEMETRY.enabled = False
        GLOBAL_TELEMETRY.reset()


# ----------------------------------------------------------------------
# the acceptance soak: >= 64 sessions, WAN profile, migrations + kill
# ----------------------------------------------------------------------


def test_chaos_soak_64_sessions_wan_profile():
    from ggrs_tpu.serve.chaos import run_chaos

    GLOBAL_TELEMETRY.enabled = True
    try:
        rep = run_chaos(
            sessions=64, ticks=60, hosts=2, entities=ENTITIES, seed=1,
            migrations=2, kill=True, kill_pause_ticks=4, flash_crowd=2,
        )
        group = rep.pop("_group")
        assert rep["sessions"] >= 64
        assert rep["desyncs"] == 0, f"chaos soak desynced: {rep}"
        # the zero-desync claim is backed by real comparisons
        assert rep["checksums_published"] > 0
        # the schedule actually ran: >= 2 live migrations, 1 kill+restore
        assert rep["migrations_done"] >= 2
        assert rep["kill"] and rep["kill"]["sessions_resumed"] == (
            rep["kill"]["sessions_suspended"]
        )
        assert group.kills == 1 and group.restores == 1
        # every migrated session resumed (its first post-handoff advance
        # was observed within the run)
        assert len(rep["migration_latency_ticks"]) == rep["migrations_done"]
        # bounded p99 queue wait under the WAN profile
        assert rep["p99_queue_wait_ticks"] <= 8, rep
        # steady-state ticks never blocked on a checksum drain
        assert rep["drain_blocked_ticks"] == 0
        # the fleet made real progress (WAN RTT throttles cross-region
        # matches below tick rate; a kill pause costs its ticks too)
        assert rep["max_frame"] >= rep["ticks"] - 8
        assert rep["min_frame"] >= rep["ticks"] // 4
        # the WAN profile actually did things
        prof = rep["profile"]
        assert prof["dropped"] > 0 and prof["reorder_spikes"] > 0
        # migration + group instruments visible through both exporters
        prom = GLOBAL_TELEMETRY.prometheus()
        snap = GLOBAL_TELEMETRY.snapshot()
        for name in ("ggrs_migrations_total", "ggrs_migration_ms"):
            assert name in prom
            assert name in snap["metrics"]
        assert snap["metrics"]["ggrs_migrations_total"]["values"][""] >= 2
    finally:
        GLOBAL_TELEMETRY.enabled = False
        GLOBAL_TELEMETRY.reset()


def test_hostgroup_backoff_jitter_schedule_pinned_by_seed():
    """The admission backoff is jittered-exponential from a SEEDED rng:
    a fixed schedule synchronizes every rejected admission in a flash
    crowd onto the same retry instants (a storm that re-collides
    forever); the seed keeps soaks reproducible. The FakeClock pins the
    exact virtual-time schedule a seed produces."""
    clock = FakeClock()
    game = ExGame(num_players=2, num_entities=ENTITIES)
    group = HostGroup.build(
        game, 1, clock=clock, max_prediction=8, num_players=2,
        max_sessions=2, idle_timeout_ms=0, max_attempts=4, backoff_ms=32,
        backoff_seed=9,
    )
    # the same seed replays the same draw sequence, each inside the
    # jittered-exponential envelope [base/2, base]
    twin = HostGroup.build(
        game, 1, clock=FakeClock(), max_prediction=8, num_players=2,
        max_sessions=2, idle_timeout_ms=0, max_attempts=4, backoff_ms=32,
        backoff_seed=9,
    )
    expected = [twin.backoff_delay_ms(a) for a in range(3)]
    for attempt, delay in enumerate(expected):
        base = 32 << attempt
        assert base // 2 <= delay <= base
    assert len(set(expected)) > 1  # jitter actually varies the draws

    net = InMemoryNetwork(clock)
    group.attach(solo_session(net, "a"))
    group.attach(solo_session(net, "b"))
    t0 = clock.now_ms()
    marks = []
    real_backoff = group._backoff

    def spying_backoff(attempt):
        real_backoff(attempt)
        marks.append(clock.now_ms() - t0)

    group._backoff = spying_backoff
    with pytest.raises(GroupSaturated):
        group.attach(solo_session(net, "overflow"))
    # the observed retry instants are exactly the seeded schedule's
    # cumulative sums — pinned, not merely bounded
    assert marks == [sum(expected[: i + 1]) for i in range(len(expected))]

    # a different seed decorrelates the schedule
    other = HostGroup.build(
        game, 1, clock=FakeClock(), max_prediction=8, num_players=2,
        max_sessions=2, idle_timeout_ms=0, max_attempts=4, backoff_ms=32,
        backoff_seed=10,
    )
    assert [other.backoff_delay_ms(a) for a in range(3)] != expected
