"""Static-analysis suite + retrace sanitizer.

Every lint rule gets at least one true-positive fixture (the rule MUST
fire) and one clean fixture (it MUST NOT) — fed through the same Repo/
run_passes entry point the CLI gate uses, so fixture behavior is gate
behavior. Then the dogfood assertion: the repo itself runs with zero
unbaselined findings. The sanitizer half covers the seeded retrace, the
telemetry wiring (ggrs_recompiles_total through both exporters +
flight-recorder events in host.telemetry()), the dispatch-budget
assertion, and a hosted warmup+serve scenario that must stay
recompile-clean under the sanitizer."""

import os

import pytest

from ggrs_tpu.analysis import (
    RULES,
    Repo,
    apply_baseline,
    format_baseline,
    parse_baseline,
    run_passes,
)
from ggrs_tpu.analysis.baseline import BaselineEntry


def rules_fired(files, passes=None):
    findings = run_passes(Repo(files=files), passes)
    for f in findings:
        assert f.rule in RULES, f"unregistered rule id {f.rule}"
    return [f.rule for f in findings], findings


# ----------------------------------------------------------------------
# determinism (DET001..DET004)
# ----------------------------------------------------------------------


def test_det001_wall_clock_fires_and_clean_passes():
    bad = {"ggrs_tpu/tpu/fx.py": (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
    )}
    rules, _ = rules_fired(bad, ["determinism"])
    assert rules == ["DET001"]
    clean = {"ggrs_tpu/tpu/fx.py": (
        "import time\n"
        "def pace():\n"
        "    return time.perf_counter()\n"  # monotonic pacing is host-side
    )}
    assert rules_fired(clean, ["determinism"])[0] == []


def test_det001_out_of_scope_module_not_linted():
    # obs/ timestamps events on purpose; the determinism scope excludes it
    files = {"ggrs_tpu/obs/fx.py": "import time\nT = time.time()\n"}
    assert rules_fired(files, ["determinism"])[0] == []


def test_det002_unseeded_rng():
    bad = {"ggrs_tpu/models/fx.py": (
        "import random\n"
        "import numpy as np\n"
        "def roll():\n"
        "    return random.randint(0, 3) + np.random.rand()\n"
        "def entropy():\n"
        "    return np.random.default_rng()\n"
    )}
    rules, _ = rules_fired(bad, ["determinism"])
    assert rules == ["DET002", "DET002", "DET002"]
    clean = {"ggrs_tpu/models/fx.py": (
        "import random\n"
        "import numpy as np\n"
        "def roll(seed):\n"
        "    rng = random.Random(seed)\n"
        "    g = np.random.default_rng(seed)\n"
        "    return rng.randint(0, 3) + g.uniform()\n"
    )}
    assert rules_fired(clean, ["determinism"])[0] == []


def test_det002_stateful_rng_draft_path():
    # the speculative-bubble-filling draft contract: every draw in a
    # draft script is a counter-based uniform of (seed, frame, player)
    # (tpu/input_model.draft_script, env/opponents.unit_uniform). A
    # draft path that keeps a STATEFUL RNG stream instead — where the
    # k-th draw depends on how many draws preceded it, so a re-draft of
    # the same anchor yields a different script — must be a DET002
    # true positive, not something the lint waves through.
    bad = {"ggrs_tpu/tpu/draftfx.py": (
        "import numpy as np\n"
        "class Drafter:\n"
        "    def __init__(self):\n"
        "        self._rng = np.random.default_rng()\n"
        "    def draft_script(self, depth):\n"
        "        # stateful stream: draw k depends on draws 0..k-1\n"
        "        return [self._rng.random() for _ in range(depth)]\n"
    )}
    rules, findings = rules_fired(bad, ["determinism"])
    assert rules == ["DET002"]
    assert findings[0].path == "ggrs_tpu/tpu/draftfx.py"
    # the shipped shape: counter-based draws keyed on (seed, frame,
    # player) — byte-identical on re-draft, nothing for the lint to say
    clean = {"ggrs_tpu/tpu/draftfx.py": (
        "from ggrs_tpu.env.opponents import unit_uniform\n"
        "def draft_script(seed, anchor, depth, players):\n"
        "    return [unit_uniform(seed, anchor + j, players)\n"
        "            for j in range(depth)]\n"
    )}
    assert rules_fired(clean, ["determinism"])[0] == []


def test_det003_id_hash():
    bad = {"ggrs_tpu/sync_layer.py": (
        "def key(cell):\n"
        "    return id(cell) ^ hash('x')\n"
    )}
    rules, _ = rules_fired(bad, ["determinism"])
    assert rules == ["DET003", "DET003"]
    clean = {"ggrs_tpu/sync_layer.py": (
        "def key(frame, slot):\n"
        "    return (frame, slot)\n"
    )}
    assert rules_fired(clean, ["determinism"])[0] == []


def test_det004_set_iteration():
    bad = {"ggrs_tpu/input_queue.py": (
        "def drain(pending):\n"
        "    out = []\n"
        "    for p in set(pending):\n"
        "        out.append(p)\n"
        "    return out + list({1, 2, 3})\n"
    )}
    rules, _ = rules_fired(bad, ["determinism"])
    assert rules == ["DET004", "DET004"]
    clean = {"ggrs_tpu/input_queue.py": (
        "def drain(pending):\n"
        "    has = 3 in set(pending)\n"  # membership: order-free
        "    return [p for p in sorted(set(pending))], has\n"
    )}
    assert rules_fired(clean, ["determinism"])[0] == []


# ----------------------------------------------------------------------
# trace discipline (TRC001..TRC004)
# ----------------------------------------------------------------------


def test_trc001_host_sync_in_traced_fn():
    bad = {"ggrs_tpu/tpu/fx.py": (
        "import jax\n"
        "import numpy as np\n"
        "def build():\n"
        "    def impl(x):\n"
        "        v = float(x)\n"
        "        h = np.asarray(x)\n"
        "        return x.item() + v\n"
        "    return jax.jit(impl)\n"
    )}
    rules, _ = rules_fired(bad, ["trace_discipline"])
    assert sorted(rules) == ["TRC001", "TRC001", "TRC001"]
    clean = {"ggrs_tpu/tpu/fx.py": (
        "import jax\n"
        "import numpy as np\n"
        "from ggrs_tpu.types import InputStatus\n"
        "def build():\n"
        "    def impl(x):\n"
        "        n = int(x.shape[0])\n"      # shape read: static
        "        k = int(InputStatus.CONFIRMED)\n"  # global enum: concrete
        "        return x * n + k\n"
        "    host = np.asarray([1, 2])\n"    # host scope: not traced
        "    return jax.jit(impl), host\n"
    )}
    assert rules_fired(clean, ["trace_discipline"])[0] == []


def test_trc002_branch_on_traced_arg_and_static_argnums():
    bad = {"ggrs_tpu/tpu/fx.py": (
        "import jax\n"
        "def build():\n"
        "    def impl(x):\n"
        "        if x > 0:\n"
        "            return x\n"
        "        return -x\n"
        "    return jax.jit(impl)\n"
    )}
    assert rules_fired(bad, ["trace_discipline"])[0] == ["TRC002"]
    # same branch, but the argument is a static jit key -> clean
    clean = {"ggrs_tpu/tpu/fx.py": (
        "import jax\n"
        "def build():\n"
        "    def impl(x, mode):\n"
        "        if mode > 0:\n"
        "            return x\n"
        "        if mode is None:\n"  # sentinel: structural, fine
        "            return x\n"
        "        return -x\n"
        "    return jax.jit(impl, static_argnums=(1,))\n"
    )}
    assert rules_fired(clean, ["trace_discipline"])[0] == []


def test_trc002_bound_method_static_argnums_skip_self():
    # static_argnums index the call-time signature of the BOUND method
    clean = {"ggrs_tpu/tpu/fx.py": (
        "import jax\n"
        "class Core:\n"
        "    def _impl(self, ring, state, nslots):\n"
        "        if nslots > 4:\n"
        "            return ring\n"
        "        return state\n"
        "    def __init__(self):\n"
        "        self.fn = jax.jit(self._impl, static_argnums=(2,))\n"
    )}
    assert rules_fired(clean, ["trace_discipline"])[0] == []


def test_trc003_closure_mutation():
    bad = {"ggrs_tpu/tpu/fx.py": (
        "import jax\n"
        "log = []\n"
        "class Core:\n"
        "    def _impl(self, x):\n"
        "        log.append(1)\n"
        "        self.cache = x\n"
        "        return x\n"
        "    def build(self):\n"
        "        return jax.jit(self._impl)\n"
    )}
    rules, _ = rules_fired(bad, ["trace_discipline"])
    assert sorted(rules) == ["TRC003", "TRC003"]
    # pallas kernels mutate Ref cells by design: not a violation
    clean = {"ggrs_tpu/tpu/fx.py": (
        "import jax\n"
        "from jax.experimental import pallas as pl\n"
        "def build(spec):\n"
        "    def kernel(x_ref, o_ref):\n"
        "        def tick(i):\n"
        "            o_ref[i] = x_ref[i] * 2\n"
        "        jax.lax.fori_loop(0, 4, lambda i, _: tick(i), None)\n"
        "    return pl.pallas_call(kernel, out_shape=spec)\n"
    )}
    assert rules_fired(clean, ["trace_discipline"])[0] == []


def test_trc003_subscript_store_through_self_attr():
    bad = {"ggrs_tpu/tpu/fx.py": (
        "import jax\n"
        "class Core:\n"
        "    def _impl(self, x):\n"
        "        self.buf[0] = x\n"
        "        return x\n"
        "    def build(self):\n"
        "        return jax.jit(self._impl)\n"
    )}
    rules, findings = rules_fired(bad, ["trace_discipline"])
    assert rules == ["TRC003"]
    assert "self.buf" in findings[0].message


def test_trc004_jit_cache_per_call():
    bad = {"ggrs_tpu/serve/fx.py": (
        "import jax\n"
        "def serve(xs):\n"
        "    outs = []\n"
        "    for x in xs:\n"
        "        outs.append(jax.jit(lambda a: a + 1)(x))\n"
        "    y = jax.jit(lambda a: a * 2)(xs[0])\n"
        "    return outs, y\n"
    )}
    rules, _ = rules_fired(bad, ["trace_discipline"])
    assert rules == ["TRC004", "TRC004"]
    clean = {"ggrs_tpu/serve/fx.py": (
        "import jax\n"
        "STEP = jax.jit(lambda a: a + 1)\n"  # module scope: one cache
        "def serve(xs):\n"
        "    return [STEP(x) for x in xs]\n"
    )}
    assert rules_fired(clean, ["trace_discipline"])[0] == []


# ----------------------------------------------------------------------
# fence discipline (FEN001)
# ----------------------------------------------------------------------

_FENCE_BAD = """
class TpuRollbackBackend:
    def __init__(self):
        self._inflight = []
    def _note_inflight(self, h):
        self._inflight.append(h)
    def sneaky_reset(self):
        self._inflight.clear()
    def sneaky_swap(self):
        self._multi_active = None
"""

_FENCE_CLEAN = """
class TpuRollbackBackend:
    def __init__(self):
        self._inflight = []
        self.beam_hits = 0
    def _note_inflight(self, h):
        self._inflight.append(h)
    def flush(self):
        self._inflight.clear()
    def anywhere(self):
        self.beam_hits += 1          # unprotected attr: free
        n = len(self._inflight)      # reads: always fine
        return n
"""


def test_fen001_fires_outside_entry_points_only():
    rules, findings = rules_fired(
        {"ggrs_tpu/tpu/backend.py": _FENCE_BAD}, ["fence"]
    )
    assert rules == ["FEN001", "FEN001"]
    assert {f.symbol for f in findings} == {
        "TpuRollbackBackend.sneaky_reset",
        "TpuRollbackBackend.sneaky_swap",
    }
    assert rules_fired(
        {"ggrs_tpu/tpu/backend.py": _FENCE_CLEAN}, ["fence"]
    )[0] == []


def test_fen001_host_never_touches_device_internals():
    bad = {"ggrs_tpu/serve/host.py": (
        "class SessionHost:\n"
        "    def hack(self):\n"
        "        self.device._inflight.clear()\n"
        "        self.device.inflight_rows = 0\n"
        "    def hack_tuple(self):\n"
        "        # the codebase's canonical write form for the stacked\n"
        "        # worlds must not slip through as tuple unpacking\n"
        "        self.device.rings, self.device.states, x, y = restore()\n"
    )}
    rules, _ = rules_fired(bad, ["fence"])
    assert rules == ["FEN001", "FEN001", "FEN001", "FEN001"]
    clean = {"ggrs_tpu/serve/host.py": (
        "class SessionHost:\n"
        "    def ok(self):\n"
        "        return self.device.poll_retired()\n"
    )}
    assert rules_fired(clean, ["fence"])[0] == []


# ----------------------------------------------------------------------
# wire contract (WIRE001..WIRE004)
# ----------------------------------------------------------------------

_MSG_PY_OK = (
    "import struct\n"
    "MSG_SYNC_REQUEST = 0\n"
    "MSG_SYNC_REPLY = 1\n"
    "_HEADER = struct.Struct('<HB')\n"
)
_EP_CPP_OK = (
    "constexpr uint8_t MSG_SYNC_REQUEST = 0;\n"
    "constexpr uint8_t MSG_SYNC_REPLY = 1;\n"
)


def test_wire001_msg_code_drift():
    bad = {
        "ggrs_tpu/network/messages.py": _MSG_PY_OK,
        "native/endpoint.cpp": (
            "constexpr uint8_t MSG_SYNC_REQUEST = 0;\n"
            "constexpr uint8_t MSG_SYNC_REPLY = 2;\n"  # drifted
        ),
    }
    rules, _ = rules_fired(bad, ["wire_contract"])
    assert "WIRE001" in rules
    clean = {
        "ggrs_tpu/network/messages.py": _MSG_PY_OK,
        "native/endpoint.cpp": _EP_CPP_OK,
    }
    assert rules_fired(clean, ["wire_contract"])[0] == []


def test_wire002_ctypes_struct_drift():
    h = (
        "struct ggrs_ep_stats {\n"
        "  int32_t send_queue_len;\n"
        "  uint32_t ping_ms;\n"
        "};\n"
    )
    bad = {
        "ggrs_tpu/native/endpoint.py": (
            "import ctypes\n"
            "class _Stats(ctypes.Structure):\n"
            "    _fields_ = [\n"
            "        ('send_queue_len', ctypes.c_int32),\n"
            "        ('ping_ms', ctypes.c_int32),\n"  # wrong sign/type
            "    ]\n"
        ),
        "native/ggrs_native.h": h,
    }
    rules, _ = rules_fired(bad, ["wire_contract"])
    assert rules == ["WIRE002"]
    clean = {
        "ggrs_tpu/native/endpoint.py": (
            "import ctypes\n"
            "class _Stats(ctypes.Structure):\n"
            "    _fields_ = [\n"
            "        ('send_queue_len', ctypes.c_int32),\n"
            "        ('ping_ms', ctypes.c_uint32),\n"
            "    ]\n"
        ),
        "native/ggrs_native.h": h,
    }
    assert rules_fired(clean, ["wire_contract"])[0] == []


def test_wire003_buffer_bound_drift():
    bad = {
        "ggrs_tpu/network/sockets.py": (
            "RECV_BUFFER_SIZE = 65536\n"
            "MAX_DATAGRAM_SIZE = min(RECV_BUFFER_SIZE, 65507)\n"
        ),
        "ggrs_tpu/native/session.py": "_WIRE_BUF_CAP = 4096\n",
    }
    rules, _ = rules_fired(bad, ["wire_contract"])
    assert "WIRE003" in rules
    clean = {
        "ggrs_tpu/network/sockets.py": (
            "RECV_BUFFER_SIZE = 65536\n"
            "MAX_DATAGRAM_SIZE = min(RECV_BUFFER_SIZE, 65507)\n"
        ),
        "ggrs_tpu/native/session.py": (
            "from ..network.sockets import RECV_BUFFER_SIZE\n"
            "_WIRE_BUF_CAP = RECV_BUFFER_SIZE\n"
        ),
    }
    assert rules_fired(clean, ["wire_contract"])[0] == []


def test_wire004_shared_constant_drift():
    bad = {
        "ggrs_tpu/network/protocol.py": "MAX_PAYLOAD = 467\n",
        "native/endpoint.cpp": "constexpr size_t MAX_PAYLOAD = 400;\n",
    }
    rules, _ = rules_fired(bad, ["wire_contract"])
    assert rules == ["WIRE004"]
    clean = {
        "ggrs_tpu/network/protocol.py": "MAX_PAYLOAD = 467\n",
        "native/endpoint.cpp": "constexpr size_t MAX_PAYLOAD = 467;\n",
    }
    assert rules_fired(clean, ["wire_contract"])[0] == []


# ----------------------------------------------------------------------
# baseline mechanics
# ----------------------------------------------------------------------


def test_baseline_roundtrip_and_ratchet():
    entries = [
        BaselineEntry(
            rule="DET001", path="ggrs_tpu/tpu/fx.py", symbol="stamp",
            justification='bench-only "timer", quoted + escaped \\ path',
            count=2,
        )
    ]
    text = format_baseline(entries, header="test header")
    parsed = parse_baseline(text)
    assert parsed == entries

    files = {"ggrs_tpu/tpu/fx.py": (
        "import time\n"
        "def stamp():\n"
        "    return time.time() + time.time() + time.time()\n"
    )}
    findings = run_passes(Repo(files=files), ["determinism"])
    assert len(findings) == 3
    fresh, suppressed, stale = apply_baseline(findings, parsed)
    # count=2 suppresses two occurrences, the third stays fresh
    assert len(suppressed) == 2 and len(fresh) == 1 and stale == []

    # a stale entry is reported once nothing matches
    fresh2, _, stale2 = apply_baseline([], parsed)
    assert fresh2 == [] and len(stale2) == 1


def test_baseline_rejects_malformed():
    with pytest.raises(Exception):
        parse_baseline("rule = \"DET001\"\n")  # key outside a table


def test_baseline_duplicate_keys_stack_not_shadow():
    # two [[finding]] entries for one key: budgets add up in file order
    entries = [
        BaselineEntry(rule="DET001", path="ggrs_tpu/tpu/fx.py",
                      symbol="stamp", justification="first"),
        BaselineEntry(rule="DET001", path="ggrs_tpu/tpu/fx.py",
                      symbol="stamp", justification="second"),
    ]
    files = {"ggrs_tpu/tpu/fx.py": (
        "import time\n"
        "def stamp():\n"
        "    return time.time() + time.time()\n"
    )}
    findings = run_passes(Repo(files=files), ["determinism"])
    assert len(findings) == 2
    fresh, suppressed, stale = apply_baseline(findings, entries)
    assert fresh == [] and len(suppressed) == 2 and stale == []


def test_baseline_trailing_backslash_roundtrips():
    entries = [BaselineEntry(
        rule="DET001", path="p.py", symbol="f",
        justification="windows path C:\\tmp\\",  # ends in a backslash
    )]
    assert parse_baseline(format_baseline(entries)) == entries


# ----------------------------------------------------------------------
# dogfood: the repo itself holds the gate
# ----------------------------------------------------------------------


def test_repo_runs_clean_against_baseline():
    repo = Repo.from_here()
    assert repo.root and os.path.isdir(os.path.join(repo.root, "ggrs_tpu"))
    findings = run_passes(repo)
    baseline_path = os.path.join(
        repo.root, "ggrs_tpu", "analysis", "baseline.toml"
    )
    entries = []
    if os.path.isfile(baseline_path):
        with open(baseline_path) as f:
            entries = parse_baseline(f.read())
    for e in entries:  # every audited entry must carry a real reason
        assert e.justification and "TODO" not in e.justification
    fresh, _, _ = apply_baseline(findings, entries)
    assert fresh == [], "unbaselined findings:\n" + "\n".join(
        f.render() for f in fresh
    )


def test_cli_exits_nonzero_on_findings(tmp_path):
    import subprocess
    import sys

    root = tmp_path / "repo"
    (root / "ggrs_tpu" / "tpu").mkdir(parents=True)
    (root / "ggrs_tpu" / "tpu" / "bad.py").write_text(
        "import time\nT = time.time()\n"
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo_root)
    proc = subprocess.run(
        [sys.executable, "-m", "ggrs_tpu.analysis", "--root", str(root),
         "--no-baseline"],
        capture_output=True, text=True, env=env,
    )
    assert proc.returncode == 1
    assert "DET001" in proc.stdout

    proc2 = subprocess.run(
        [sys.executable, "-m", "ggrs_tpu.analysis", "--root", str(root),
         "--passes", "fence"],
        capture_output=True, text=True, env=env,
    )
    assert proc2.returncode == 0


# ----------------------------------------------------------------------
# retrace sanitizer
# ----------------------------------------------------------------------


@pytest.fixture
def sanitizer():
    from ggrs_tpu.analysis.sanitize import (
        install_sanitizer,
        uninstall_sanitizer,
    )

    san = install_sanitizer()
    san.reset()
    yield san
    san.reset()
    uninstall_sanitizer()


def test_sanitizer_catches_seeded_retrace(sanitizer):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return x + 1

    step(jnp.ones(3))
    sanitizer.freeze("test warmup")
    for n in (4, 5, 6):
        step(jnp.ones(n))
    assert len(sanitizer.recompiles) == 3
    assert all(
        "test_analysis.py" in e.provenance() for e in sanitizer.recompiles
    )
    report = sanitizer.report()
    assert "RECOMPILE" in report and "test_analysis.py" in report


def test_sanitizer_telemetry_counters_and_events(sanitizer):
    import jax
    import jax.numpy as jnp

    from ggrs_tpu.obs import GLOBAL_TELEMETRY

    GLOBAL_TELEMETRY.enabled = True
    try:
        GLOBAL_TELEMETRY.registry.reset()
        GLOBAL_TELEMETRY.recorder.clear()

        @jax.jit
        def step(x):
            return x * 2

        step(jnp.ones(2))
        sanitizer.freeze("telemetry test")
        step(jnp.ones(5))  # one recompile

        reg = GLOBAL_TELEMETRY.registry
        assert reg.get("ggrs_program_compiles_total").value == 2
        assert reg.get("ggrs_recompiles_total").value == 1
        prom = GLOBAL_TELEMETRY.prometheus()
        assert "ggrs_recompiles_total 1" in prom
        snap = GLOBAL_TELEMETRY.snapshot()
        assert snap["metrics"]["ggrs_recompiles_total"]["values"][""] == 1
        kinds = [e["kind"] for e in snap["events"]]
        assert "program_compile" in kinds
        assert "unexpected_recompile" in kinds
        recomp = [
            e for e in snap["events"] if e["kind"] == "unexpected_recompile"
        ][0]
        assert "test_analysis.py" in recomp["provenance"]
    finally:
        GLOBAL_TELEMETRY.enabled = False
        GLOBAL_TELEMETRY.reset()


def test_sanitizer_dispatch_budget_raises(sanitizer):
    import jax
    import jax.numpy as jnp

    from ggrs_tpu.errors import RetraceBudgetExceeded

    @jax.jit
    def prog(x):
        return x.sum()

    for n in (2, 3, 4):  # 3 cached programs
        prog(jnp.ones(n))
    sanitizer.check_dispatch_budget({"prog": prog}, budget=3)  # at bound: ok
    with pytest.raises(RetraceBudgetExceeded) as exc:
        sanitizer.check_dispatch_budget({"prog": prog}, budget=2)
    assert "dispatch-bucket budget" in str(exc.value)
    assert "test_analysis.py" in str(exc.value)


def test_second_warmup_thaws_then_refreezes(sanitizer):
    """A later backend's warmup is legitimate compilation: it must lift a
    standing freeze for its duration instead of reporting its own grid
    compile as phantom mid-serve recompiles."""
    from ggrs_tpu.models.ex_game import ExGame
    from ggrs_tpu.tpu import TpuRollbackBackend

    sanitizer.freeze("earlier backend's warmup")
    backend = TpuRollbackBackend(
        ExGame(num_players=2, num_entities=8), max_prediction=2,
        num_players=2,
    )
    backend.warmup()
    assert sanitizer.recompiles == [], sanitizer.report()
    assert len(sanitizer.compiles) > 0
    assert sanitizer.freeze_label == "TpuRollbackBackend.warmup"


def test_warmup_refreezes_even_when_it_raises(sanitizer):
    """A failed warmup must not leave the sanitizer thawed process-wide:
    recompile detection stays armed for the cores that ARE serving."""
    from ggrs_tpu.models.ex_game import ExGame
    from ggrs_tpu.tpu import TpuRollbackBackend

    backend = TpuRollbackBackend(
        ExGame(num_players=2, num_entities=8), max_prediction=2,
        num_players=2,
    )
    sanitizer.freeze("pre-existing freeze")
    backend._warmup_impl = lambda: (_ for _ in ()).throw(
        RuntimeError("device fell over mid-warmup")
    )
    with pytest.raises(RuntimeError):
        backend.warmup()
    assert sanitizer.frozen_at is not None
    assert sanitizer.freeze_label == "TpuRollbackBackend.warmup"


def test_hosted_serve_recompile_clean_under_sanitizer(sanitizer):
    """The acceptance gate's positive control: warmup compiles the whole
    megabatch grid, then an actual hosted serve (solo P2P lanes ticking
    through the megabatch scheduler) must not compile ANYTHING — and the
    in-dispatch budget assertion must hold throughout."""
    from ggrs_tpu import PlayerType, SessionBuilder
    from ggrs_tpu.models.ex_game import ExGame
    from ggrs_tpu.network.sockets import InMemoryNetwork
    from ggrs_tpu.obs import GLOBAL_TELEMETRY
    from ggrs_tpu.serve import SessionHost
    from ggrs_tpu.utils.clock import FakeClock

    GLOBAL_TELEMETRY.enabled = True
    GLOBAL_TELEMETRY.registry.reset()
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = SessionHost(
        ExGame(num_players=2, num_entities=8),
        max_prediction=4,
        num_players=2,
        max_sessions=4,
        clock=clock,
        warmup=True,  # compiles the grid, then freezes the sanitizer
    )
    assert sanitizer.frozen_at is not None
    assert sanitizer.freeze_label == "MultiSessionDeviceCore.warmup"
    assert len(sanitizer.compiles) > 0

    keys = []
    for i in range(3):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(4)
        )
        for h in range(2):
            b = b.add_player(PlayerType.local(), h)
        session = b.start_p2p_session(net.socket(("solo", i)))
        keys.append(host.attach(session))
    for t in range(24):
        for i, key in enumerate(keys):
            for h in range(2):
                host.submit_input(key, h, bytes([(t * 3 + h + i) % 16]))
        host.tick()
        clock.advance(16)
    try:
        host.device.block_until_ready()
        assert host.device.megabatches > 0
        assert sanitizer.recompiles == [], (
            "hosted serve recompiled mid-serve:\n" + sanitizer.report()
        )
        # the counter rides host.telemetry() and both exporters, at zero
        snap = host.telemetry()
        assert snap["metrics"]["ggrs_recompiles_total"]["values"][""] == 0
        assert snap["metrics"]["ggrs_program_compiles_total"]["values"][""] > 0
        assert "ggrs_recompiles_total 0" in GLOBAL_TELEMETRY.prometheus()
    finally:
        GLOBAL_TELEMETRY.enabled = False
        GLOBAL_TELEMETRY.reset()


# ----------------------------------------------------------------------
# alloc (ALLOC001..ALLOC004) — fixtures opt in via __ggrs_hot__
# ----------------------------------------------------------------------


def test_alloc001_per_iteration_container_fires_and_scratch_clean():
    bad = {"ggrs_tpu/serve/fx.py": (
        "__ggrs_hot__ = ('Host.tick',)\n"
        "class Host:\n"
        "    def tick(self, lanes):\n"
        "        for lane in lanes:\n"
        "            rows = [lane.row]\n"
        "            self.emit(rows)\n"
        "    def emit(self, rows):\n"
        "        pass\n"
    )}
    rules, _ = rules_fired(bad, ["alloc"])
    assert rules == ["ALLOC001"]
    clean = {"ggrs_tpu/serve/fx.py": (
        "__ggrs_hot__ = ('Host.tick',)\n"
        "class Host:\n"
        "    def __init__(self):\n"
        "        self._scratch = []\n"
        "    def tick(self, lanes):\n"
        "        scratch = self._scratch\n"
        "        scratch.clear()\n"
        "        for lane in lanes:\n"
        "            scratch.append(lane.row)\n"
        "        self.emit(scratch)\n"
        "    def emit(self, rows):\n"
        "        pass\n"
    )}
    assert rules_fired(clean, ["alloc"])[0] == []


def test_alloc001_reaches_through_callees():
    # the allocation sits two calls below the declared hot entry
    bad = {"ggrs_tpu/serve/fx.py": (
        "__ggrs_hot__ = ('Host.tick',)\n"
        "class Host:\n"
        "    def tick(self, lanes):\n"
        "        self._pump(lanes)\n"
        "    def _pump(self, lanes):\n"
        "        self._drain(lanes)\n"
        "    def _drain(self, lanes):\n"
        "        for lane in lanes:\n"
        "            lane.out = {'k': lane.row}\n"
    )}
    rules, found = rules_fired(bad, ["alloc"])
    assert rules == ["ALLOC001"]
    assert found[0].symbol == "Host._drain"


def test_alloc001_cold_contexts_do_not_fire():
    # lazy-init guard, except handler and raise argument are cold by
    # contract: they allocate only off the steady state
    clean = {"ggrs_tpu/serve/fx.py": (
        "__ggrs_hot__ = ('Host.tick',)\n"
        "class Host:\n"
        "    def tick(self, lanes):\n"
        "        for lane in lanes:\n"
        "            q = self.groups.get(lane.key)\n"
        "            if q is None:\n"
        "                q = self.groups[lane.key] = []\n"
        "            q.append(lane.row)\n"
        "            try:\n"
        "                lane.step()\n"
        "            except RuntimeError:\n"
        "                self.failed = [lane.key]\n"
    )}
    assert rules_fired(clean, ["alloc"])[0] == []


def test_alloc002_per_call_closures():
    bad = {"ggrs_tpu/serve/fx.py": (
        "__ggrs_hot__ = ('Host.tick',)\n"
        "class Host:\n"
        "    def tick(self, rows):\n"
        "        rows.sort(key=lambda r: r.slot)\n"
    )}
    rules, _ = rules_fired(bad, ["alloc"])
    assert rules == ["ALLOC002"]
    clean = {"ggrs_tpu/serve/fx.py": (
        "__ggrs_hot__ = ('Host.tick',)\n"
        "class Host:\n"
        "    def tick(self, rows):\n"
        "        rows.sort(key=self._slot_key)\n"
        "    def _slot_key(self, r):\n"
        "        return r.slot\n"
    )}
    assert rules_fired(clean, ["alloc"])[0] == []


def test_alloc003_string_building_vs_pooled_bytes():
    bad = {"ggrs_tpu/serve/fx.py": (
        "__ggrs_hot__ = ('pump',)\n"
        "def pump(rows):\n"
        "    return f'batch of {len(rows)}'\n"
    )}
    rules, _ = rules_fired(bad, ["alloc"])
    assert rules == ["ALLOC003"]
    # b''.join is the sanctioned pooled byte-staging flush, and strings
    # on the raise path are cold
    clean = {"ggrs_tpu/serve/fx.py": (
        "__ggrs_hot__ = ('pump',)\n"
        "def pump(chunks, n):\n"
        "    if n < 0:\n"
        "        raise ValueError(f'bad row count {n}')\n"
        "    return b''.join(chunks)\n"
    )}
    assert rules_fired(clean, ["alloc"])[0] == []


def test_alloc004_packing_and_sorted_in_loop():
    bad = {"ggrs_tpu/serve/fx.py": (
        "__ggrs_hot__ = ('Host.tick',)\n"
        "class Host:\n"
        "    def tick(self, *rows, **opts):\n"
        "        for group in self.groups:\n"
        "            for e in sorted(group):\n"
        "                e.go()\n"
    )}
    rules, _ = rules_fired(bad, ["alloc"])
    assert sorted(rules) == ["ALLOC004", "ALLOC004"]
    clean = {"ggrs_tpu/serve/fx.py": (
        "__ggrs_hot__ = ('Host.tick',)\n"
        "class Host:\n"
        "    def tick(self, rows, opts):\n"
        "        batch = sorted(rows)\n"
        "        for e in batch:\n"
        "            e.go()\n"
    )}
    assert rules_fired(clean, ["alloc"])[0] == []


def test_alloc_unseeded_module_not_linted():
    # no __ggrs_hot__ and not in the entry table: nothing is hot
    files = {"ggrs_tpu/serve/fx.py": (
        "def helper(rows):\n"
        "    for r in rows:\n"
        "        out = [r]\n"
    )}
    assert rules_fired(files, ["alloc"])[0] == []


# ----------------------------------------------------------------------
# exceptions (EXC001..EXC002)
# ----------------------------------------------------------------------


def test_exc001_untyped_raise_fires_and_bridge_clean():
    bad = {"ggrs_tpu/tpu/fx.py": (
        "def f(n):\n"
        "    raise ValueError('bad: %d' % n)\n"
    )}
    rules, _ = rules_fired(bad, ["exceptions"])
    assert rules == ["EXC001"]
    # the bridge hierarchy resolves across files by the repo-wide
    # class fixpoint: FxError IS a GGRSError even though the raise
    # site's module never mentions GGRSError
    clean = {
        "ggrs_tpu/tpu/fx_err.py": (
            "class FxError(GGRSError, ValueError):\n"
            "    pass\n"
        ),
        "ggrs_tpu/tpu/fx.py": (
            "def f(n):\n"
            "    raise FxError('bad row count')\n"
        ),
    }
    assert rules_fired(clean, ["exceptions"])[0] == []


def test_exc001_reraise_idioms_are_clean():
    clean = {"ggrs_tpu/tpu/fx.py": (
        "class FxError(GGRSError, ValueError):\n"
        "    pass\n"
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except FxError as e:\n"
        "        note(e)\n"
        "        raise\n"
        "def h():\n"
        "    try:\n"
        "        g()\n"
        "    except FxError as e:\n"
        "        raise e.with_traceback(None)\n"
        "def k():\n"
        "    err = FxError('wedged')\n"
        "    note(err)\n"
        "    raise err\n"
    )}
    assert rules_fired(clean, ["exceptions"])[0] == []


def test_exc001_dynamic_expression_fires():
    bad = {"ggrs_tpu/tpu/fx.py": (
        "def f(bag):\n"
        "    raise bag[0]\n"
    )}
    rules, found = rules_fired(bad, ["exceptions"])
    assert rules == ["EXC001"]
    assert "dynamic expression" in found[0].message


def test_exc002_swallowing_broad_except():
    bad = {"ggrs_tpu/tpu/fx.py": (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )}
    rules, _ = rules_fired(bad, ["exceptions"])
    assert rules == ["EXC002"]
    # recording a flight event, or re-raising, redeems the broad catch
    clean = {"ggrs_tpu/tpu/fx.py": (
        "def f(tel):\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as exc:\n"
        "        tel.record('fx_failed', error=str(exc))\n"
        "def h():\n"
        "    try:\n"
        "        g()\n"
        "    except BaseException:\n"
        "        raise\n"
    )}
    assert rules_fired(clean, ["exceptions"])[0] == []


def test_cli_json_records(tmp_path):
    import json
    import subprocess
    import sys

    root = tmp_path / "repo"
    (root / "ggrs_tpu" / "tpu").mkdir(parents=True)
    (root / "ggrs_tpu" / "tpu" / "bad.py").write_text(
        "import time\ndef f():\n    raise ValueError(time.time())\n"
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo_root)
    proc = subprocess.run(
        [sys.executable, "-m", "ggrs_tpu.analysis", "--root", str(root),
         "--no-baseline", "--json"],
        capture_output=True, text=True, env=env,
    )
    assert proc.returncode == 1  # exit codes unchanged by --json
    recs = json.loads(proc.stdout)
    assert recs, "expected findings as JSON records"
    for rec in recs:
        assert set(rec) == {"rule", "path", "line", "symbol", "message"}
        assert isinstance(rec["line"], int)
    assert {r["rule"] for r in recs} == {"DET001", "EXC001"}


# ----------------------------------------------------------------------
# allocation sanitizer
# ----------------------------------------------------------------------


@pytest.fixture
def alloc_sanitizer_cleanup():
    from ggrs_tpu.analysis.sanitize import thaw_allocations
    from ggrs_tpu.obs import GLOBAL_TELEMETRY

    GLOBAL_TELEMETRY.enabled = True
    GLOBAL_TELEMETRY.registry.reset()
    GLOBAL_TELEMETRY.recorder.clear()
    yield
    thaw_allocations()
    GLOBAL_TELEMETRY.enabled = False
    GLOBAL_TELEMETRY.reset()


def test_alloc_sanitizer_seeded_regression_trips(alloc_sanitizer_cleanup):
    from ggrs_tpu.analysis.sanitize import (
        active_alloc_sanitizer,
        freeze_allocations,
    )
    from ggrs_tpu.obs import GLOBAL_TELEMETRY

    san = freeze_allocations(budget_blocks=256, label="seeded test")
    assert active_alloc_sanitizer() is san

    for _ in range(20):  # healthy ticks: transient churn only
        scratch = [0] * 8
        scratch.clear()
        san.note_tick()
    assert san.trips == [], san.report()

    hoard = []  # the seeded regression: retained growth every tick
    for _ in range(3):
        hoard.extend(object() for _ in range(5000))
        san.note_tick()
    assert len(san.trips) >= 1, san.report()
    ev = san.trips[0]
    assert ev.blocks > 256 and ev.budget == 256
    assert "test_analysis.py" in ev.provenance()  # tracemalloc names us

    reg = GLOBAL_TELEMETRY.registry
    assert reg.get("ggrs_alloc_budget_trips_total").value >= 1
    hist = reg.get("ggrs_alloc_per_tick").snapshot()["values"][""]
    assert hist["count"] == 23
    snap = GLOBAL_TELEMETRY.snapshot()
    trip_events = [
        e for e in snap["events"] if e["kind"] == "alloc_budget_trip"
    ]
    assert trip_events and "test_analysis.py" in trip_events[0]["provenance"]
    prom = GLOBAL_TELEMETRY.prometheus()
    assert "ggrs_alloc_budget_trips_total" in prom
    assert "ggrs_alloc_per_tick_count" in prom


def test_alloc_sanitizer_thaw_disarms(alloc_sanitizer_cleanup):
    from ggrs_tpu.analysis.sanitize import (
        active_alloc_sanitizer,
        freeze_allocations,
        thaw_allocations,
    )

    san = freeze_allocations(budget_blocks=1, label="thaw test")
    thaw_allocations()
    assert active_alloc_sanitizer() is None
    keep = [object() for _ in range(4096)]
    san.note_tick()  # no-op while thawed
    assert san.trips == [] and keep


def test_alloc_sanitizer_healthy_hosted_serve_silent(alloc_sanitizer_cleanup):
    """The acceptance gate's positive control: a hosted steady-state
    serve, ticked through SessionHost.tick (which carries the
    note_tick probe), must stay under the DEFAULT budget — the tick
    path's zero-steady-state-allocation claim, asserted at runtime."""
    from ggrs_tpu import PlayerType, SessionBuilder
    from ggrs_tpu.analysis.sanitize import freeze_allocations
    from ggrs_tpu.models.ex_game import ExGame
    from ggrs_tpu.network.sockets import InMemoryNetwork
    from ggrs_tpu.serve import SessionHost
    from ggrs_tpu.utils.clock import FakeClock

    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = SessionHost(
        ExGame(num_players=2, num_entities=8),
        max_prediction=4,
        num_players=2,
        max_sessions=4,
        clock=clock,
        warmup=True,
    )
    keys = []
    for i in range(3):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(4)
        )
        for h in range(2):
            b = b.add_player(PlayerType.local(), h)
        session = b.start_p2p_session(net.socket(("solo", i)))
        keys.append(host.attach(session))

    def drive(ticks, base):
        for t in range(ticks):
            for i, key in enumerate(keys):
                for h in range(2):
                    host.submit_input(
                        key, h, bytes([(base + t * 3 + h + i) % 16])
                    )
            host.tick()
            clock.advance(16)

    drive(8, 0)  # warm: caches, pools and lazy slots fill here
    san = freeze_allocations(label="hosted steady state")
    drive(24, 8)
    host.device.block_until_ready()
    assert san.ticks_seen == 24
    assert san.trips == [], (
        "steady-state host tick blew the allocation budget:\n"
        + san.report()
    )


# ----------------------------------------------------------------------
# transfer guard
# ----------------------------------------------------------------------


def test_transfer_guard_trips_on_planted_sync(sanitizer):
    import jax.numpy as jnp

    from ggrs_tpu.analysis.sanitize import transfer_guard_scope
    from ggrs_tpu.errors import GGRSError, ImplicitHostTransfer
    from ggrs_tpu.obs import GLOBAL_TELEMETRY

    GLOBAL_TELEMETRY.enabled = True
    GLOBAL_TELEMETRY.registry.reset()
    GLOBAL_TELEMETRY.recorder.clear()
    try:
        x = jnp.arange(4.0)
        assert float(x.sum()) == 6.0  # warm, unguarded
        sanitizer.freeze("transfer test")
        with pytest.raises(ImplicitHostTransfer) as ei:
            with transfer_guard_scope("resident drive"):
                float(x.sum())  # the planted implicit sync
        assert isinstance(ei.value, GGRSError)  # fleet isolation routes it
        assert "resident drive" in str(ei.value)
        assert "test_analysis.py" in str(ei.value)

        with pytest.raises(ImplicitHostTransfer):
            with transfer_guard_scope("dispatch"):
                x.sum().item()

        snap = GLOBAL_TELEMETRY.snapshot()
        kinds = [e["kind"] for e in snap["events"]]
        assert kinds.count("implicit_host_transfer") == 2
        reg = GLOBAL_TELEMETRY.registry
        assert reg.get("ggrs_transfer_guard_trips_total").value == 2
        # both patches restored once the scope closed
        assert float(x.sum()) == 6.0
        assert x.sum().item() == 6.0
    finally:
        GLOBAL_TELEMETRY.enabled = False
        GLOBAL_TELEMETRY.reset()


def test_transfer_guard_inert_unfrozen_and_uninstalled(sanitizer):
    import jax.numpy as jnp

    from ggrs_tpu.analysis.sanitize import transfer_guard_scope

    x = jnp.ones(3)
    # installed but NOT frozen: warmup may read buffers freely
    assert sanitizer.frozen_at is None
    with transfer_guard_scope("dispatch"):
        assert float(x.sum()) == 3.0

    # frozen: host reads OUTSIDE the guarded region stay legal (the
    # drain pass's pooled readback runs outside the scope)
    sanitizer.freeze("inert test")
    assert float(x.sum()) == 3.0
