"""Depth-adaptive dispatch: bitwise parity of the depth-bucketed programs
against the full-window references, across every rollback depth and all
three dispatch paths (T=1 content routing, the lazy multi-tick scan, the
cross-session megabatch with its zero-rollback fast path), plus the jit
cache's O(log N x log W) bucket-budget bound under a lossy hosted soak.

The contract under test: routing a row (or a whole buffered batch / a
megabatch group) to the smallest depth bucket covering its last active
slot must change NOTHING observable — checksums, ring bytes, live state —
only the device work dispatched."""

import jax
import numpy as np
import pytest

from ggrs_tpu import SessionBuilder
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.tpu import TpuRollbackBackend
from ggrs_tpu.tpu.backend import MultiSessionDeviceCore
from ggrs_tpu.tpu.resim import ResimCore

ENTITIES = 16
PLAYERS = 2


def make_core(max_prediction=8):
    return ResimCore(
        ExGame(num_players=PLAYERS, num_entities=ENTITIES),
        max_prediction=max_prediction,
        num_players=PLAYERS,
    )


def depth_row(core, rng, depth, frame):
    """One packed tick row of rollback depth `depth` (0 = a plain
    zero-rollback tick: no load, one advance, dense saves), with real
    inputs in every active slot and a save per advanced frame."""
    W = core.window
    inputs = rng.integers(0, 16, size=(W, PLAYERS, 1), dtype=np.uint8)
    statuses = np.zeros((W, PLAYERS), dtype=np.int32)
    save_slots = np.full((W,), core.scratch_slot, dtype=np.int32)
    count = max(depth, 1)
    for i in range(count):
        save_slots[i] = (frame + i) % core.ring_len
    return core.pack_tick_row(
        do_load=depth > 0,
        load_slot=frame % core.ring_len,
        inputs=inputs,
        statuses=statuses,
        save_slots=save_slots,
        advance_count=count,
        start_frame=frame,
    )


def fetch(core):
    return (
        jax.device_get(core.ring),
        jax.device_get(core.state),
    )


def assert_cores_equal(a, b, msg=""):
    (ring_a, state_a), (ring_b, state_b) = fetch(a), fetch(b)
    for k in state_a:
        np.testing.assert_array_equal(
            np.asarray(ring_a[k]), np.asarray(ring_b[k]),
            err_msg=f"{msg} ring[{k}]",
        )
        np.testing.assert_array_equal(
            np.asarray(state_a[k]), np.asarray(state_b[k]),
            err_msg=f"{msg} state[{k}]",
        )


def test_t1_depth_routing_bitwise_across_depths():
    """T=1: the content router (branchless depth variants for rollback /
    multi-advance rows, cond for trivial rows) vs the full-window cond
    program, one tick per rollback depth 0..max_prediction — checksums,
    ring bytes and live state identical after every tick."""
    routed, full = make_core(), make_core()
    assert routed._tick_branchless_fn is not None
    # force the trivial-row windowed-cond route (entity-gated off on toy
    # worlds purely for compile economics) so its parity is pinned too
    routed._t1_windowed = True
    rng_a, rng_b = np.random.default_rng(7), np.random.default_rng(7)
    frame = 0
    for depth in range(routed.max_prediction + 1):
        row_a = depth_row(routed, rng_a, depth, frame)
        row_b = depth_row(full, rng_b, depth, frame)
        his_a, los_a = routed.tick_row(row_a)
        # the full-window reference: the cond program, no routing
        full.ring, full.state, full.verify, his_b, los_b = full._tick_fn(
            full.ring, full.state, row_b, full.verify
        )
        np.testing.assert_array_equal(np.asarray(his_a), np.asarray(his_b))
        np.testing.assert_array_equal(np.asarray(los_a), np.asarray(los_b))
        assert_cores_equal(routed, full, f"depth={depth}")
        frame += max(depth, 1)


def test_multi_tick_depth_routing_bitwise_mixed_buffers():
    """The lazy multi-tick scan at the depth variant covering the
    buffer's deepest row vs the full-window scan, over buffers mixing
    every rollback depth 0..max_prediction — checksums [T, W], ring and
    state identical."""
    routed, full = make_core(), make_core()
    rng_a, rng_b = np.random.default_rng(11), np.random.default_rng(11)
    # three buffers with different max depths so several variants route
    for depths in ([0, 0, 1, 0], [2, 0, 3, 1], list(range(9))):
        frame = 0
        rows_a, rows_b, last_active = [], [], 0
        for d in depths:
            rows_a.append(depth_row(routed, rng_a, d, frame))
            rows_b.append(depth_row(full, rng_b, d, frame))
            frame += max(d, 1)
            last_active = max(last_active, max(d, 1))
        his_a, los_a = routed.tick_multi(
            np.stack(rows_a), last_active=last_active
        )
        his_b, los_b = full.tick_multi(np.stack(rows_b))  # full window
        np.testing.assert_array_equal(np.asarray(his_a), np.asarray(his_b))
        np.testing.assert_array_equal(np.asarray(los_a), np.asarray(los_b))
        assert_cores_equal(routed, full, f"depths={depths}")


@pytest.mark.parametrize("lazy_ticks", [16])
def test_lazy16_backend_parity_routing_on_vs_off(lazy_ticks):
    """End to end through TpuRollbackBackend(lazy_ticks=16): the same
    forced-rollback SyncTest request stream with depth routing on vs
    off — final state and every saved checksum bit-identical."""

    def backend(depth_routing):
        return TpuRollbackBackend(
            ExGame(num_players=PLAYERS, num_entities=ENTITIES),
            max_prediction=6,
            num_players=PLAYERS,
            lazy_ticks=lazy_ticks,
            depth_routing=depth_routing,
        )

    def synctest():
        return (
            SessionBuilder(input_size=1)
            .with_num_players(PLAYERS)
            .with_max_prediction_window(6)
            .with_check_distance(4)
            .start_synctest_session()
        )

    routed, full = backend(True), backend(False)
    sess_r, sess_f = synctest(), synctest()
    cells_r, cells_f = [], []
    for t in range(25):
        for h in range(PLAYERS):
            buf = bytes([(t * (3 + h) + h) % 16])
            sess_r.add_local_input(h, buf)
            sess_f.add_local_input(h, buf)
        rr, rf = sess_r.advance_frame(), sess_f.advance_frame()
        routed.handle_requests(rr)
        full.handle_requests(rf)
        cells_r += [r.cell for r in rr if hasattr(r, "cell")]
        cells_f += [r.cell for r in rf if hasattr(r, "cell")]
    sr, sf = routed.state_numpy(), full.state_numpy()
    for k in sr:
        np.testing.assert_array_equal(
            np.asarray(sr[k]), np.asarray(sf[k]), err_msg=f"state[{k}]"
        )
    assert len(cells_r) == len(cells_f) > 0
    for cr, cf in zip(cells_r, cells_f):
        assert cr.frame == cf.frame
        assert cr.checksum == cf.checksum, f"checksum at frame {cr.frame}"


def test_megabatch_mixed_depths_bitwise_vs_full_window():
    """A hosted-style 8-session megabatch with mixed rollback depths
    (0..8): depth-grouped dispatch (zero-rollback fast program + one
    windowed program per occupied depth bucket) vs ONE full-window
    megabatch — per-slot checksums, stacked rings and stacked states all
    bit-identical."""
    N = 8

    def device(depth_routing):
        return MultiSessionDeviceCore(
            ExGame(num_players=PLAYERS, num_entities=ENTITIES),
            max_prediction=8,
            num_players=PLAYERS,
            capacity=N,
            depth_routing=depth_routing,
        )

    dev_a, dev_b = device(True), device(False)
    core_a, core_b = dev_a.core, dev_b.core
    depths = [0, 3, 0, 8, 1, 0, 5, 0]  # zero-rollback rows dominate
    rng_a, rng_b = np.random.default_rng(23), np.random.default_rng(23)
    frame = 4
    rows_a = [depth_row(core_a, rng_a, d, frame) for d in depths]
    rows_b = [depth_row(core_b, rng_b, d, frame) for d in depths]

    # routed: group like the host scheduler (fast + per depth bucket)
    groups = {}
    for slot, (row, d) in enumerate(zip(rows_a, depths)):
        la = max(d, 1)
        gkey = (
            "fast"
            if dev_a.fast_eligible(row, la)
            else dev_a.depth_bucket_for(la)
        )
        groups.setdefault(gkey, []).append((slot, row, la))
    assert "fast" in groups and len(groups) >= 3  # genuinely mixed
    got = {}
    for gkey, group in groups.items():
        entries = [(slot, row) for slot, row, _ in group]
        if gkey == "fast":
            batch, _ = dev_a.dispatch(entries, fast=True)
        else:
            batch, _ = dev_a.dispatch(
                entries, last_active=max(la for _, _, la in group)
            )
        for k, (slot, _, _) in enumerate(group):
            got[slot] = (batch, k)

    # reference: one full-window megabatch
    batch_b, _ = dev_b.dispatch(list(enumerate(rows_b)))

    W = core_a.window
    for slot in range(N):
        batch, k = got[slot]
        for i in range(W):
            assert batch.resolve(k * W + i) == batch_b.resolve(
                slot * W + i
            ), f"checksum slot={slot} window={i}"
    dev_a.block_until_ready()
    dev_b.block_until_ready()
    for leaf_a, leaf_b in zip(
        jax.tree.leaves(dev_a.rings), jax.tree.leaves(dev_b.rings)
    ):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(leaf_a)),
            np.asarray(jax.device_get(leaf_b)),
        )
    for leaf_a, leaf_b in zip(
        jax.tree.leaves(dev_a.states), jax.tree.leaves(dev_b.states)
    ):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(leaf_a)),
            np.asarray(jax.device_get(leaf_b)),
        )


@pytest.mark.slow  # the 64-session serve soak carries the same bound in
# tier-1; this denser mixed-depth variant rides the full gate only
def test_lossy_soak_jit_cache_within_bucket_budget():
    """A lossy hosted soak must keep the megabatch program population
    inside the O(log N x log W) grid depth routing guarantees — fleet
    churn, mixed depths and backpressure must never mint programs beyond
    (row buckets) x (depth buckets + fast)."""
    from ggrs_tpu.serve.loadgen import run_loadgen

    rep = run_loadgen(
        sessions=12, ticks=30, entities=ENTITIES, seed=3, loss=0.05,
        latency_ms=20, jitter_ms=10,
    )
    host = rep.pop("_host")
    assert rep["desyncs"] == 0
    mega = host.device.megabatch_programs()
    assert len(mega) > 0
    assert len(mega) <= host.device.dispatch_bucket_budget(), (
        f"megabatch programs escaped the bucket grid: {sorted(mega)}"
    )
    # every minted program names a grid point: a configured row bucket
    # x (a configured depth bucket | 0 = the fast path)
    for bucket, d, _count in mega:
        assert bucket in host.device.buckets
        assert d == 0 or d in host.device.depth_buckets
    host.drain()


def test_depth_telemetry_instruments_record_fast_path():
    """The obs wiring: a hosted zero-rollback fleet must land megabatch
    dispatches in the depth histogram's le=1 bucket (the fast-path
    marker the dispatch smoke gate asserts) and grow the padded-slot
    waste counter; both must ride the exporters."""
    from ggrs_tpu import PlayerType
    from ggrs_tpu.network.sockets import InMemoryNetwork
    from ggrs_tpu.obs import GLOBAL_TELEMETRY
    from ggrs_tpu.serve import SessionHost
    from ggrs_tpu.utils.clock import FakeClock

    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = SessionHost(
        ExGame(num_players=PLAYERS, num_entities=ENTITIES),
        max_prediction=8,
        num_players=PLAYERS,
        max_sessions=4,
        clock=clock,
    )

    def solo(addr):
        b = SessionBuilder(input_size=1).with_num_players(PLAYERS)
        for h in range(PLAYERS):
            b = b.add_player(PlayerType.local(), h)
        return b.start_p2p_session(net.socket(addr))

    keys = [host.attach(solo(f"s{i}")) for i in range(3)]
    GLOBAL_TELEMETRY.enabled = True
    try:
        depth0 = GLOBAL_TELEMETRY.registry.get("ggrs_dispatch_depth")
        waste0 = GLOBAL_TELEMETRY.registry.get(
            "ggrs_padded_slot_waste"
        ).value
        fast0 = depth0.snapshot()["values"].get("", {"buckets": {}})[
            "buckets"
        ].get("1", 0)
        for t in range(6):
            for key in keys:
                for h in range(PLAYERS):
                    host.submit_input(key, h, bytes([(t + h) % 16]))
            host.tick()
            clock.advance(16)
        snap = GLOBAL_TELEMETRY.registry.get(
            "ggrs_dispatch_depth"
        ).snapshot()["values"][""]
        assert snap["buckets"]["1"] > fast0, (
            "zero-rollback hosted traffic never took the fast path"
        )
        waste = GLOBAL_TELEMETRY.registry.get("ggrs_padded_slot_waste")
        assert waste.value > waste0
        # both exporters carry the new series
        text = GLOBAL_TELEMETRY.prometheus()
        assert "ggrs_dispatch_depth_bucket" in text
        assert "ggrs_padded_slot_waste" in text
        assert "ggrs_dispatch_depth" in GLOBAL_TELEMETRY.snapshot()["metrics"]
    finally:
        GLOBAL_TELEMETRY.enabled = False
