"""Entity-tiled pallas kernel (ggrs_tpu/tpu/pallas_tiled.py): full-carry
bit parity with the XLA scan across multiple tiles and batch boundaries,
divergence detection through the post-pass verdict, and the tileability
gate. Interpreter mode on the CPU mesh; real-TPU parity at 1M entities is
exercised by bench.py's roofline phase."""

import numpy as np
import pytest

import jax
import jax.tree_util as jtu

from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.tpu import TpuSyncTestSession

P = 2


def drive(backend, script, entities, check_distance, batches=3, **kw):
    sess = TpuSyncTestSession(
        ExGame(P, entities),
        num_players=P,
        check_distance=check_distance,
        flush_interval=10_000,
        backend=backend,
        **kw,
    )
    t = script.shape[0] // batches
    for i in range(batches):
        sess.advance_frames(script[i * t : (i + 1) * t])
    return sess


def assert_carry_equal(a, b):
    la = jtu.tree_leaves_with_path(jax.device_get(a))
    lb = jtu.tree_leaves(jax.device_get(b))
    assert len(la) == len(lb)
    for (path, x), y in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=jtu.keystr(path)
        )


@pytest.mark.parametrize("check_distance,entities", [(2, 1024), (5, 2048)])
def test_tiled_carry_parity_with_xla(check_distance, entities):
    """Multiple tiles (auto tile sizing) through multiple batches: the
    cross-tile checksum accumulation, ring streaming and batch-boundary
    carry must all be bit-identical to the XLA scan."""
    rng = np.random.default_rng(7)
    script = rng.integers(0, 16, size=(36, P, 1), dtype=np.uint8)
    xla = drive("xla", script, entities, check_distance)
    tiled = drive("pallas-tiled-interpret", script, entities, check_distance)
    assert_carry_equal(xla.carry, tiled.carry)
    xla.check()
    tiled.check()


def test_tiled_multi_tile_explicit():
    """Force several tiles explicitly (tile_rows=8 over 16 rows)."""
    from ggrs_tpu.tpu.pallas_tiled import PallasTiledSyncTestCore

    core = PallasTiledSyncTestCore(
        ExGame(P, 2048), P, 3, interpret=True, tile_rows=8
    )
    assert core.n_tiles == 2
    sess = TpuSyncTestSession(
        ExGame(P, 2048), num_players=P, check_distance=3,
        flush_interval=10_000, backend="xla",
    )
    rng = np.random.default_rng(8)
    script = rng.integers(0, 16, size=(14, P, 1), dtype=np.uint8)
    import jax.numpy as jnp

    out = core.batch(sess.carry, jnp.asarray(script))
    sess.advance_frames(script)
    assert_carry_equal(sess.carry, out)


@pytest.mark.parametrize("sharded", [False, True])
def test_tiled_detects_injected_divergence(sharded):
    """Unsharded kernel verdict and the psum'd sharded verdict both latch a
    mismatch injected into (one shard's slice of) the ring."""
    from ggrs_tpu.errors import MismatchedChecksum
    from ggrs_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8) if sharded else None
    rng = np.random.default_rng(9)
    script = rng.integers(0, 16, size=(24, P, 1), dtype=np.uint8)
    sess = TpuSyncTestSession(
        ExGame(P, 2048), num_players=P, check_distance=4,
        flush_interval=10_000, backend="pallas-tiled-interpret", mesh=mesh,
    )
    sess.advance_frames(script[:12])
    sess.check()
    ring = dict(sess.carry["ring"])
    slot = (sess.current_frame - 4) % sess.ring_len
    ring["pos"] = ring["pos"].at[slot, 0, 0].add(7)
    sess.carry = {**sess.carry, "ring": ring}
    sess.advance_frames(script[12:])
    with pytest.raises(MismatchedChecksum):
        sess.check()


@pytest.mark.parametrize("check_distance", [2, 5])
def test_sharded_tiled_carry_parity(check_distance):
    """The flagship composition: shard_map over the `entity` axis running
    one local tiled kernel per device, partial checksums psum'd. Full-carry
    bit parity vs the SHARDED XLA scan (same mesh) and the UNSHARDED tiled
    kernel across batch boundaries."""
    from ggrs_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)  # (beam=2, entity=4)
    entities = 2048  # 512/shard = 4 rows/shard
    rng = np.random.default_rng(11)
    script = rng.integers(0, 16, size=(36, P, 1), dtype=np.uint8)
    sharded_tiled = drive(
        "pallas-tiled-interpret", script, entities, check_distance, mesh=mesh
    )
    sharded_xla = drive("xla", script, entities, check_distance, mesh=mesh)
    plain_tiled = drive(
        "pallas-tiled-interpret", script, entities, check_distance
    )
    assert_carry_equal(sharded_xla.carry, sharded_tiled.carry)
    assert_carry_equal(plain_tiled.carry, sharded_tiled.carry)
    sharded_tiled.check()
    # the state actually shards: each device holds entities/4 rows
    shard = sharded_tiled.carry["state"]["pos"].addressable_shards[0]
    assert shard.data.shape[0] == entities // mesh.shape["entity"]


def test_tiled_reduce_model_single_tile_only():
    """Arena's per-team centroids are cross-entity reductions: legal on
    the tiled kernel ONLY as one whole-world tile (inline sums complete);
    a shard's slice — where the sums would be silently local — is
    rejected."""
    from ggrs_tpu.models.arena import Arena
    from ggrs_tpu.tpu.pallas_tiled import PallasTiledSyncTestCore

    core = PallasTiledSyncTestCore(Arena(P, 1024), P, 3, interpret=True)
    assert core.n_tiles == 1  # forced whole-world tile
    with pytest.raises(AssertionError, match="shard"):
        PallasTiledSyncTestCore(
            Arena(P, 1024), P, 3, interpret=True, local_entities=512
        )
