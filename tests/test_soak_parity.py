"""Randomized soak: the native and Python stacks must emit identical
request streams, events and replica histories under randomized fault
schedules, inputs, disconnect injections and desync detection — many seeds,
one deterministic world per seed (clock, network RNG, input script).

This is the fuzzing arm of the parity suite: test_native_session_core.py
pins specific scenarios; this file sweeps the configuration space.
"""

import random

import pytest

from ggrs_tpu import (
    DesyncDetection,
    PlayerType,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.native import available
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.utils.clock import FakeClock
from stubs import GameStub

pytestmark = pytest.mark.skipif(
    not available(), reason="native library not built (make -C native)"
)

TICKS = 70


def scenario(seed):
    rng = random.Random(seed)
    return {
        "latency": rng.choice([0, 20, 40, 60]),
        "jitter": rng.choice([0, 10, 30]),
        "loss": rng.choice([0.0, 0.1, 0.25]),
        "input_delay": rng.choice([0, 1, 3]),
        "max_prediction": rng.choice([6, 8, 10]),
        "desync": rng.choice([None, 10, 16]),
        # disconnect player 1 on session 0 midway (or never)
        "disconnect_tick": rng.choice([None, None, 25, 40]),
        "inputs": [
            [rng.randrange(0, 16) for _ in range(2)] for _ in range(TICKS)
        ],
    }


def run_stack(use_native, sc, seed):
    clock = FakeClock()
    net = InMemoryNetwork(
        clock, latency_ms=sc["latency"], jitter_ms=sc["jitter"],
        loss=sc["loss"], seed=seed,
    )

    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(sc["max_prediction"])
            .with_input_delay(sc["input_delay"])
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        if sc["desync"]:
            b = b.with_desync_detection_mode(DesyncDetection.on(sc["desync"]))
        if use_native:
            b = b.with_native_sessions(True)
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        return b.start_p2p_session(net.socket(my_addr))

    s0, s1 = build("a", "b", 0), build("b", "a", 1)
    for _ in range(400):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        s0.events()
        s1.events()
        clock.advance(20)
        if (
            s0.current_state() == SessionState.RUNNING
            and s1.current_state() == SessionState.RUNNING
        ):
            break
    else:
        raise AssertionError(f"seed {seed}: failed to synchronize")

    from ggrs_tpu.errors import GGRSError
    from test_native_session_core import req_sig

    g0, g1 = GameStub(), GameStub()
    stream = []
    disconnected = False
    for t in range(TICKS):
        if t == sc["disconnect_tick"]:
            s0.disconnect_player(1)
            disconnected = True
        row = []
        for s, g, handle in ((s0, g0, 0), (s1, g1, 1)):
            if disconnected and handle == 1:
                # the disconnected side keeps polling but stops advancing
                # (its own session will error once s0's disconnect status
                # propagates); parity only covers s0 from here
                s.poll_remote_clients()
                row.append(None)
                continue
            s.add_local_input(handle, bytes([sc["inputs"][t][handle]]))
            try:
                reqs = s.advance_frame()
            except GGRSError as exc:
                row.append(("error", type(exc).__name__))
                continue
            g.handle_requests(reqs)
            row.append(req_sig(reqs))
        events = [type(e).__name__ for e in s0.events()] + [
            type(e).__name__ for e in (s1.events() if not disconnected else [])
        ]
        stream.append((row, sorted(events)))
        clock.advance(16)
    return stream, g0, g1, s0, s1, disconnected


@pytest.mark.parametrize("seed", range(8))
def test_soak_native_python_stream_parity(seed):
    sc = scenario(seed)
    py = run_stack(False, sc, seed)
    nat = run_stack(True, sc, seed)

    for t, (py_t, nat_t) in enumerate(zip(py[0], nat[0])):
        assert py_t == nat_t, f"seed {seed}: streams diverged at tick {t}"

    # replicas converge on the confirmed prefix (when nobody disconnected)
    _, g0, g1, s0, s1, disconnected = py
    if not disconnected:
        confirmed = min(s0.confirmed_frame(), s1.confirmed_frame())
        assert confirmed > TICKS // 3
        for f in range(1, confirmed + 1):
            assert g0.history[f] == g1.history[f]
