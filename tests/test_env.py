"""RollbackEnv: the batched RL environment over the rollback core.

Parity strategy (mirrors the serve suite): an env step IS a
confirmed-input session tick, so the same deterministic input scripts
through (a) a solo local session + TpuRollbackBackend and (b) a
RollbackEnv world must produce bit-identical per-step checksums and
device state. On top of that: auto-reset slot reuse must be
indistinguishable from a fresh slot, a seeded snapshot→branch→restore
search episode must replay bit-exactly, the env instruments must ride
both exporters, the hosted (mixed-traffic) env must match its
standalone twin while live sessions keep advancing, and the jit cache
must stay frozen after warmup."""

import numpy as np
import pytest

from ggrs_tpu import PlayerType, SaveGameState, SessionBuilder
from ggrs_tpu.env import (
    InputModelOpponent,
    RollbackEnv,
    ScriptedOpponent,
    held_value_trace,
)
from ggrs_tpu.errors import HostFull, InvalidRequest
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.obs import GLOBAL_TELEMETRY
from ggrs_tpu.serve import SessionHost
from ggrs_tpu.tpu import TpuRollbackBackend
from ggrs_tpu.utils.clock import FakeClock

ENTITIES = 16


def make_game():
    return ExGame(num_players=2, num_entities=ENTITIES)


def make_env(n=4, **kw):
    return RollbackEnv(make_game(), num_envs=n, **kw)


def agent_script(t, w):
    return (t * 3 + w) % 16


def opp_script(t, w):
    return (t * 5 + 2 * w + 1) % 16


def opp_for(n):
    return ScriptedOpponent(
        lambda t, n_envs: np.array(
            [opp_script(t, w) for w in range(n_envs)], np.uint8
        )
    )


def assert_states_equal(a, b, msg=""):
    for k in a:
        np.testing.assert_array_equal(
            np.asarray(a[k]), np.asarray(b[k]), err_msg=f"{msg} state[{k}]"
        )


# ----------------------------------------------------------------------
# bitwise parity vs the solo session tick stream
# ----------------------------------------------------------------------


def test_env_step_matches_solo_session_stream():
    """Identical scripts through a solo local session fulfilled by
    TpuRollbackBackend and through RollbackEnv worlds: every step's
    post-step checksum and the final device state must be bit-identical
    — any divergence is the env dispatch path's fault."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    N, T = 2, 12

    ref_cs = {}
    ref_states = []
    for w in range(N):
        b = SessionBuilder(input_size=1).with_num_players(2)
        for h in range(2):
            b = b.add_player(PlayerType.local(), h)
        sess = b.start_p2p_session(net.socket(("ref", w)))
        backend = TpuRollbackBackend(
            make_game(), max_prediction=8, num_players=2
        )
        for t in range(T):
            sess.add_local_input(0, bytes([agent_script(t, w)]))
            sess.add_local_input(1, bytes([opp_script(t, w)]))
            reqs = sess.advance_frame()
            backend.handle_requests(reqs)
            # resolve getters per tick: ring slots recycle every
            # ring_len frames
            for r in reqs:
                if isinstance(r, SaveGameState):
                    ref_cs[(w, r.frame)] = r.cell.checksum_getter()()
        ref_states.append(backend.state_numpy())

    env = make_env(
        N, opponents={1: opp_for(N)}, record_checksums=True
    )
    env.reset()
    compared = 0
    for t in range(T):
        acts = np.array([[agent_script(t, w)] for w in range(N)], np.uint8)
        env.step(acts)
        got = env.step_checksums()
        for w in range(N):
            want = ref_cs.get((w, t + 1))
            if want is not None:
                assert want == got[w], f"world {w} frame {t + 1}"
                compared += 1
    assert compared >= N * (T - 1)  # the stream really was checked
    for w in range(N):
        assert_states_equal(
            ref_states[w], env.state_numpy(w), msg=f"world {w}"
        )


# ----------------------------------------------------------------------
# auto-reset: slot reuse vs a fresh slot
# ----------------------------------------------------------------------


def test_auto_reset_slot_reuse_matches_fresh_slot():
    """A world that finished an episode and auto-reset must be bitwise
    indistinguishable from a freshly built env driven by the second
    episode's script alone — slot reuse leaks nothing."""
    N, EP, TAIL = 2, 5, 4  # tail < EP: no second truncation mid-compare
    env = make_env(
        N, agent_handles=(0, 1), episode_len=EP, auto_reset=True
    )
    env.reset()

    def acts(fn, t):
        return np.stack(
            [
                np.array([[fn(t, w, 0)] for w in range(N)], np.uint8),
                np.array([[fn(t, w, 1)] for w in range(N)], np.uint8),
            ],
            axis=1,
        )

    ep1 = lambda t, w, h: (t * 3 + w + h) % 16
    ep2 = lambda t, w, h: (t * 7 + 2 * w + 3 * h) % 16
    dones = 0
    for t in range(EP):
        _, _, done, _ = env.step(acts(ep1, t))
        dones += int(done.sum())
    assert dones == N  # every world truncated exactly at the limit
    assert env.episodes_total == N
    for t in range(TAIL):
        _, _, done, _ = env.step(acts(ep2, t))
        assert not done.any()

    fresh = make_env(N, agent_handles=(0, 1), episode_len=EP)
    fresh.reset()
    for t in range(TAIL):
        fresh.step(acts(ep2, t))
    assert env.checksums() == fresh.checksums()
    for w in range(N):
        assert_states_equal(
            env.state_numpy(w), fresh.state_numpy(w), msg=f"world {w}"
        )


# ----------------------------------------------------------------------
# snapshot → branch → restore determinism
# ----------------------------------------------------------------------


def test_snapshot_branch_restore_determinism():
    """A seeded search episode: snapshot, play a branch, restore, replay
    the same branch — both passes must be bit-identical (checksums and
    state), opponents included."""
    trace = held_value_trace([1, 4, 2, 8, 1, 4, 2, 8, 5, 4])
    env = make_env(
        4, opponents={1: InputModelOpponent(trace, seed=11)}
    )
    env.reset()
    for t in range(6):
        env.step(np.full((4, 1), agent_script(t, 0), np.uint8))
    snap = env.snapshot()
    base_cs = env.checksums()

    def branch(script):
        out = []
        for t in range(4):
            env.step(np.full((4, 1), script(t), np.uint8))
            out.append(env.checksums())
        return out

    first = branch(lambda t: (t * 9 + 2) % 16)
    env.restore(snap)
    assert env.checksums() == base_cs  # restore really rewound
    replay = branch(lambda t: (t * 9 + 2) % 16)
    assert first == replay
    # a DIFFERENT branch from the same snapshot diverges (the snapshot
    # is live state, not a stuck copy)
    env.restore(snap)
    other = branch(lambda t: (t * 11 + 5) % 16)
    assert other != first
    env.release(snap)
    # released ring slots recycle; exhausting them raises typed errors
    snaps = [env.snapshot() for _ in range(env.snapshot_capacity)]
    with pytest.raises(InvalidRequest):
        env.snapshot()
    for s in snaps:
        env.release(s)
    with pytest.raises(InvalidRequest):
        env.restore(snaps[0])  # released handles are dead


def test_env_checkpoint_roundtrip(tmp_path):
    """save()/restore_from(): a resumed env continues bit-exactly — the
    stacked worlds, episode bookkeeping and per-world opponent state all
    ride the utils/checkpoint artifact."""
    trace = held_value_trace([1, 4, 2, 8, 1, 4, 2, 8, 5, 4])

    def build():
        return make_env(
            3,
            opponents={1: InputModelOpponent(trace, seed=5)},
            episode_len=9,
        )

    env = build()
    env.reset()
    for t in range(7):
        env.step(np.full((3, 1), agent_script(t, 1), np.uint8))
    path = str(tmp_path / "env.npz")
    env.save(path)
    for t in range(5):
        env.step(np.full((3, 1), (t * 9 + 4) % 16, np.uint8))
    want = env.checksums()

    resumed = RollbackEnv.restore_from(
        path,
        make_game(),
        opponents={1: InputModelOpponent(trace, seed=5)},
    )
    assert resumed._t == 7 and resumed.steps_total == 21
    for t in range(5):
        resumed.step(np.full((3, 1), (t * 9 + 4) % 16, np.uint8))
    assert resumed.checksums() == want
    for w in range(3):
        assert_states_equal(
            env.state_numpy(w), resumed.state_numpy(w), msg=f"world {w}"
        )


def test_world_reset_invalidates_live_snapshots():
    """Resetting a world zeroes its ring — every outstanding snapshot
    handle must die with a typed error on restore (never a silent rewind
    into zeroed bytes), and its ring slot must recycle."""
    env = make_env(2, agent_handles=(0, 1), episode_len=4)
    env.reset()
    env.step(np.full((2, 2, 1), 3, np.uint8))
    snap = env.snapshot()
    free_before = len(env._free_ring)
    for t in range(4):  # crosses the episode limit -> auto-reset
        env.step(np.full((2, 2, 1), (t + 5) % 16, np.uint8))
    assert not snap.valid
    assert len(env._free_ring) == free_before + 1
    with pytest.raises(InvalidRequest):
        env.restore(snap)
    # explicit reset() kills handles the same way
    snap2 = env.snapshot()
    env.reset()
    with pytest.raises(InvalidRequest):
        env.restore(snap2)


def test_record_checksums_reserves_the_ring():
    env = make_env(2, record_checksums=True)
    env.reset()
    with pytest.raises(InvalidRequest):
        env.snapshot()


# ----------------------------------------------------------------------
# instruments / telemetry
# ----------------------------------------------------------------------


@pytest.fixture
def telemetry():
    tel = GLOBAL_TELEMETRY
    tel.reset()
    tel.enabled = True
    try:
        yield tel
    finally:
        tel.enabled = False
        tel.reset()


def test_env_instruments_ride_both_exporters(telemetry):
    N, EP, T = 4, 3, 7
    env = make_env(N, agent_handles=(0, 1), episode_len=EP)
    env.reset()
    for t in range(T):
        env.step(
            np.full((N, 2, 1), (t * 3 + 1) % 16, np.uint8)
        )
    reg = telemetry.registry
    assert reg.get("ggrs_env_steps_total").value == N * T
    # two full episode waves (steps 3 and 6) finished
    assert reg.get("ggrs_env_episodes_total").value == 2 * N
    hist = reg.get("ggrs_env_episode_len").snapshot()["values"][""]
    assert hist["count"] == 2 * N
    # the env section rides telemetry(), and both exporters carry the
    # instruments with zero exporter code (registry-driven)
    snap = env.telemetry()
    assert snap["env"]["steps_total"] == N * T
    assert snap["env"]["episodes_total"] == 2 * N
    assert snap["metrics"]["ggrs_env_steps_total"]["values"][""] == N * T
    prom = telemetry.prometheus()
    assert "ggrs_env_steps_total" in prom
    assert "ggrs_env_episode_len_bucket" in prom
    import json

    json.loads(telemetry.to_json())


# ----------------------------------------------------------------------
# hosted mixed traffic: env rows share the host megabatch
# ----------------------------------------------------------------------


def solo_session(net, addr):
    b = SessionBuilder(input_size=1).with_num_players(2)
    for h in range(2):
        b = b.add_player(PlayerType.local(), h)
    return b.start_p2p_session(net.socket(addr))


def test_hosted_env_shares_megabatch_with_sessions():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = SessionHost(
        make_game(), max_prediction=8, num_players=2, max_sessions=8,
        clock=clock,
    )
    k0 = host.attach(solo_session(net, "a"))
    k1 = host.attach(solo_session(net, "b"))
    env = host.attach_env(3, agent_handles=(0, 1))
    env.reset()
    T = 10

    def acts(t):
        return np.stack(
            [
                np.array([[agent_script(t, w)] for w in range(3)], np.uint8),
                np.array([[opp_script(t, w)] for w in range(3)], np.uint8),
            ],
            axis=1,
        )

    for t in range(T):
        for h in (0, 1):
            host.submit_input(k0, h, bytes([(t * 3 + h) % 16]))
            host.submit_input(k1, h, bytes([(t * 7 + h + 2) % 16]))
        env.step(acts(t))  # ONE host tick serves env AND session rows
        clock.advance(16)

    # live sessions advanced on the env's ticks
    assert host._lanes[k0].current_frame == T
    assert host._lanes[k1].current_frame == T
    # the merged dispatches actually coalesced: 2 session rows + 3 env
    # rows per host tick (plus the env's own reset-less steps)
    dev = host.device
    assert dev.rows_dispatched / dev.megabatches > 1.0

    # the hosted worlds are bitwise twins of a standalone env
    twin = make_env(3, agent_handles=(0, 1))
    twin.reset()
    for t in range(T):
        twin.step(acts(t))
    assert env.checksums() == twin.checksums()
    for w in range(3):
        assert_states_equal(
            env.state_numpy(w), twin.state_numpy(w), msg=f"world {w}"
        )

    # host telemetry folds the env section in
    snap = host.telemetry()
    assert snap["host"]["envs"][0]["num_envs"] == 3
    assert snap["host"]["envs"][0]["mixed_traffic"] is True

    # slot accounting: env slots block admission and free on detach
    free_before = len(host._free_slots)
    with pytest.raises(HostFull):
        host.attach_env(free_before + 1)
    host.detach_env(env)
    assert len(host._free_slots) == free_before + 3


def test_hosted_env_snapshot_restore():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = SessionHost(
        make_game(), max_prediction=8, num_players=2, max_sessions=6,
        clock=clock,
    )
    key = host.attach(solo_session(net, "a"))
    env = host.attach_env(2, agent_handles=(0, 1))
    env.reset()

    def acts(t):
        return np.full((2, 2, 1), (t * 3 + 1) % 16, np.uint8)

    for t in range(4):
        for h in (0, 1):
            host.submit_input(key, h, bytes([(t * 3 + h) % 16]))
        env.step(acts(t))
        clock.advance(16)
    snap = env.snapshot()
    for t in range(3):
        env.step(acts(t + 4))
    c1 = env.checksums()
    env.restore(snap)
    for t in range(3):
        env.step(acts(t + 4))
    assert env.checksums() == c1
    # the hosted session kept its own frame count through the env's
    # snapshot/restore dispatches (disjoint slots)
    assert host._lanes[key].current_frame == 4


# ----------------------------------------------------------------------
# jit discipline: nothing compiles after warmup
# ----------------------------------------------------------------------


def test_env_jit_cache_frozen_after_warmup():
    env = make_env(
        8,
        opponents={1: ScriptedOpponent(lambda t, n: (t * 5 + 3) % 16)},
        episode_len=5,
        warmup=True,
    )
    dev = env._device

    def cache_sizes():
        return (
            dev._dispatch_fn._cache_size()
            + dev._dispatch_fast_fn._cache_size()
            + dev._reset_mask_fn._cache_size()
            + env._obs_fn._cache_size()
            + env._checksum_fn._cache_size()
        )

    warm = cache_sizes()
    assert (
        dev._dispatch_fn._cache_size() + dev._dispatch_fast_fn._cache_size()
        <= dev.dispatch_bucket_budget()
    )
    env.reset()
    for t in range(12):  # auto-resets at 5 and 10
        env.step(np.full((8, 1), (t * 3) % 16, np.uint8))
    snap = env.snapshot()
    env.step(np.full((8, 1), 7, np.uint8))
    env.restore(snap)
    env.release(snap)
    env.checksums()
    assert cache_sizes() == warm, "steady-state env work compiled a program"


def test_env_lint_coverage():
    """ggrs_tpu/env/ is inside the determinism pass's scope: a wall-clock
    read planted at an env path must be flagged (the coverage the PR's
    linter satellite promises)."""
    from ggrs_tpu.analysis import determinism
    from ggrs_tpu.analysis.engine import Repo

    repo = Repo(files={
        "ggrs_tpu/env/planted.py": (
            "import time\n"
            "def act(t):\n"
            "    return time.time()\n"
        ),
    })
    findings = determinism.run(repo)
    assert any(
        f.rule == "DET001" and f.path == "ggrs_tpu/env/planted.py"
        for f in findings
    )
