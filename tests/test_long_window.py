"""Long-context scaling: the rollback window is this domain's sequence
axis (SURVEY.md §5 — its "context length" is max_prediction). The fused
scan's masked fixed-length design means one compilation covers every
depth; these tests push far past the BASELINE configs' 16 frames and
check bit-parity against the oracle at depth 48.
"""

import numpy as np

from ggrs_tpu import SessionBuilder
from ggrs_tpu.models import arena, ex_game

PLAYERS = 2
ENTITIES = 64
WINDOW = 49  # check_distance 48 < max_prediction 49
CHECK_DISTANCE = 48


def test_48_frame_rollback_window_matches_oracle():
    from ggrs_tpu.tpu import TpuRollbackBackend

    backend = TpuRollbackBackend(
        ex_game.ExGame(PLAYERS, ENTITIES), max_prediction=WINDOW,
        num_players=PLAYERS,
    )
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(WINDOW)
        .with_check_distance(CHECK_DISTANCE)
        .start_synctest_session()
    )
    rng = np.random.default_rng(7)
    inputs = rng.integers(0, 16, size=(70, PLAYERS, 1), dtype=np.uint8)
    for f in range(70):
        for h in range(PLAYERS):
            sess.add_local_input(h, bytes(inputs[f, h]))
        backend.handle_requests(sess.advance_frame())

    host = ex_game.init_oracle(PLAYERS, ENTITIES)
    statuses = np.zeros(PLAYERS, dtype=np.int32)
    for f in range(70):
        host = ex_game.step_oracle(host, inputs[f].reshape(-1), statuses, PLAYERS)
    dev = backend.state_numpy()
    for k in host:
        assert np.array_equal(np.asarray(dev[k]), host[k]), f"{k} diverged"


def test_48_frame_window_fused_session_with_arena():
    """Deep windows x the second model family x the fused batch session."""
    from ggrs_tpu.tpu import TpuSyncTestSession

    sess = TpuSyncTestSession(
        arena.Arena(PLAYERS, ENTITIES), num_players=PLAYERS,
        check_distance=CHECK_DISTANCE,
    )
    rng = np.random.default_rng(11)
    sess.advance_frames(rng.integers(0, 64, size=(60, PLAYERS, 1), dtype=np.uint8))
    sess.check()
