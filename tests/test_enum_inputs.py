"""Enum-typed inputs across the stack (reference:
tests/test_synctest_session_enum.rs + tests/stubs_enum.rs).

The input POD contract is byte strings; an "enum input" is a sparse set of
valid byte patterns. These tests prove the queue / prediction / compression
/ wire machinery is byte-exact — every input a session hands the game
decodes to a valid enum member, including predicted repeats, and peers
converge on identical enum histories over a lossy network.
"""

import random

import pytest

from ggrs_tpu import PlayerType, SessionBuilder, SessionState
from ggrs_tpu.native import available
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.utils.clock import FakeClock
from stubs import EnumInput, GameStubEnum

NATIVE_PARAMS = [False] + ([True] if available() else [])


def script(frame, handle):
    return EnumInput.encode(
        EnumInput.VALUES[(frame * (handle + 2) + handle) % len(EnumInput.VALUES)]
    )


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
@pytest.mark.parametrize("input_delay", [0, 2])
def test_synctest_with_enum_inputs(use_native, input_delay):
    """(tests/test_synctest_session_enum.rs) Forced rollbacks resimulate
    enum inputs byte-exactly; GameStubEnum raises on any invalid pattern."""
    b = (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_check_distance(4)
        .with_input_delay(input_delay)
    )
    if use_native:
        b = b.with_native_sessions(True)
    sess = b.start_synctest_session()
    game = GameStubEnum()
    for frame in range(40):
        for handle in range(2):
            sess.add_local_input(handle, script(frame, handle))
        game.handle_requests(sess.advance_frame())
    assert game.gs.frame == 40


def test_enum_decode_rejects_invalid_patterns():
    with pytest.raises(ValueError):
        EnumInput.decode(b"\x07")


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
def test_p2p_enum_inputs_over_lossy_network(use_native):
    """Enum bytes survive XOR-delta + RLE + resend over a lossy wire; both
    replicas decode identical enum sequences on the confirmed prefix."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=30, jitter_ms=20, loss=0.15, seed=23)

    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        if use_native:
            b = b.with_native_sessions(True)
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        return b.start_p2p_session(net.socket(my_addr))

    s1, s2 = build("a", "b", 0), build("b", "a", 1)
    for _ in range(400):
        s1.poll_remote_clients()
        s2.poll_remote_clients()
        clock.advance(20)
        if (
            s1.current_state() == SessionState.RUNNING
            and s2.current_state() == SessionState.RUNNING
        ):
            break
    g1, g2 = GameStubEnum(), GameStubEnum()
    for frame in range(60):
        s1.add_local_input(0, script(frame, 0))
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, script(frame, 1))
        g2.handle_requests(s2.advance_frame())
        s1.events()
        s2.events()
        clock.advance(16)
    for _ in range(10):
        s1.poll_remote_clients()
        s2.poll_remote_clients()
        clock.advance(16)
    s1.add_local_input(0, script(60, 0))
    g1.handle_requests(s1.advance_frame())
    s2.add_local_input(1, script(60, 1))
    g2.handle_requests(s2.advance_frame())

    confirmed = min(s1.confirmed_frame(), s2.confirmed_frame())
    assert confirmed > 30
    for f in range(1, confirmed + 1):
        assert g1.history[f] == g2.history[f], f"enum replicas diverged at {f}"
