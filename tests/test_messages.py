"""Wire codec roundtrips for every message type."""

import pytest

from ggrs_tpu.network.messages import (
    ChecksumReport,
    DecodeError,
    InputAck,
    InputMsg,
    KeepAlive,
    Message,
    QualityReply,
    QualityReport,
    SyncReply,
    SyncRequest,
    decode_message,
    encode_message,
)
from ggrs_tpu.sync_layer import ConnectionStatus


BODIES = [
    SyncRequest(random_request=0xDEADBEEF),
    SyncReply(random_reply=12345),
    InputMsg(
        peer_connect_status=[ConnectionStatus(False, 17), ConnectionStatus(True, -1)],
        disconnect_requested=True,
        start_frame=42,
        ack_frame=-1,
        bytes_=b"\x01\x02\x03\x00\x00",
    ),
    InputAck(ack_frame=99),
    QualityReport(frame_advantage=-3, ping=123456789),
    QualityReply(pong=987654321),
    ChecksumReport(checksum=(1 << 100) + 17, frame=1000),
    KeepAlive(),
]


@pytest.mark.parametrize("body", BODIES, ids=lambda b: type(b).__name__)
def test_roundtrip(body):
    msg = Message(magic=0xABCD, body=body)
    out = decode_message(encode_message(msg))
    assert out.magic == msg.magic
    if isinstance(body, InputMsg):
        got = out.body
        assert got.start_frame == body.start_frame
        assert got.ack_frame == body.ack_frame
        assert got.disconnect_requested == body.disconnect_requested
        assert got.bytes_ == body.bytes_
        assert got.peer_connect_status == body.peer_connect_status
    else:
        assert out.body == body


def test_garbage_rejected():
    with pytest.raises(DecodeError):
        decode_message(b"")
    with pytest.raises(DecodeError):
        decode_message(b"\x01\x02\xff")  # unknown body type
    with pytest.raises(DecodeError):
        decode_message(encode_message(Message(1, SyncRequest(5)))[:-2])  # truncated
