"""Pallas tick kernel (ggrs_tpu/tpu/pallas_resim.py): ResimCore's generic
control-word tick — the P2P request path's program — on the entity-tiled
kernel. Bit parity with the XLA scan is the whole contract: random
rollback depths, partial saves, disconnect substitution, device-verify
history, the lazy multi-tick buffer, and live sessions must all be
indistinguishable across backends."""

import numpy as np
import pytest

import jax
import jax.tree_util as jtu

from ggrs_tpu import SessionBuilder
from ggrs_tpu.models.arena import Arena
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.models.swarm import Swarm
from ggrs_tpu.tpu import TpuRollbackBackend
from ggrs_tpu.tpu.resim import ResimCore
from ggrs_tpu.types import InputStatus

P = 2


def drive_random(game, tick_backend, batches=8, rows_per_batch=3, seed=7,
                 mod=16, max_prediction=6):
    """Session-shaped random control streams dispatched as MULTI-ROW
    batches (T > 1 is where the pallas kernel actually engages — lone
    ticks route to the XLA scan by design): random rollback depths up to
    max_prediction - 1 with dense saving (the invariant real sessions
    maintain), occasional disconnect statuses. A spin-up of plain rows
    first grows the frame past the window so the deepest depths are
    actually reachable (frame only nets +1 per row)."""
    core = ResimCore(game, max_prediction=max_prediction, num_players=P,
                     device_verify=True, tick_backend=tick_backend)
    W = core.window
    out = []
    frame = 0
    r = np.random.default_rng(seed)
    deepest = 0
    for batch in range(batches + 1):
        rows = []
        n_rows = max_prediction + 2 if batch == 0 else rows_per_batch
        for _ in range(n_rows):
            depth = 0 if batch == 0 else int(r.integers(0, max_prediction))
            do_load = depth > 0 and frame > depth
            count = depth + 1 if do_load else 1
            start = frame - depth if do_load else frame
            if do_load:
                deepest = max(deepest, depth)
            inputs = np.zeros((W, P, 1), np.uint8)
            statuses = np.zeros((W, P), np.int32)
            for i in range(count):
                inputs[i] = r.integers(0, mod, (P, 1))
                if r.random() < 0.15:
                    statuses[i, r.integers(0, P)] = int(
                        InputStatus.DISCONNECTED
                    )
            slots = np.full((W,), core.scratch_slot, np.int32)
            for i in range(count):
                slots[i] = (start + i) % core.ring_len
            rows.append(
                core.pack_tick_row(
                    do_load, (start % core.ring_len) if do_load else 0,
                    inputs, statuses, slots, count, start_frame=start,
                )
            )
            frame = start + count
        his, los = core.tick_multi(np.stack(rows))
        out.append((np.asarray(his), np.asarray(los)))
    # the stream must actually exercise deep rollbacks, not just shallow
    # ones that the smaller-window tests already cover
    assert deepest >= max_prediction - 2, (deepest, max_prediction)
    return core, out


def assert_core_equal(a, b):
    la = jtu.tree_leaves_with_path(
        jax.device_get({"ring": a.ring, "state": a.state, "verify": a.verify})
    )
    lb = jtu.tree_leaves(
        jax.device_get({"ring": b.ring, "state": b.state, "verify": b.verify})
    )
    assert len(la) == len(lb)
    for (path, x), y in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=jtu.keystr(path)
        )


@pytest.mark.parametrize(
    "Game,mod", [(ExGame, 16), (Swarm, 128), (Arena, 64)]
)
def test_tick_kernel_bit_parity_with_xla(Game, mod):
    """All three families; arena exercises the reduction-phase single-tile
    path (inline full-plane centroids inside the kernel — P2P resim states
    are fresh, so no per-frame cache applies) plus in-kernel disconnect
    substitution against the XLA status branch."""
    game = Game(P, 512)
    a, ca = drive_random(game, "pallas-interpret", mod=mod)
    b, cb = drive_random(game, "xla", mod=mod)
    for t, ((h1, l1), (h2, l2)) in enumerate(zip(ca, cb)):
        np.testing.assert_array_equal(h1, h2, err_msg=f"his tick {t}")
        np.testing.assert_array_equal(l1, l2, err_msg=f"los tick {t}")
    assert_core_equal(a, b)


def test_tick_kernel_deep_window_parity():
    """A 16-frame prediction window (W=18, 18-slot ring): the VMEM tile
    sizing and the frame clamp past advance_count hold at real depth
    (the driver asserts rollbacks >= max_prediction - 2 actually ran)."""
    game = ExGame(P, 1024)
    a, ca = drive_random(game, "pallas-interpret", batches=6,
                         rows_per_batch=2, seed=13, max_prediction=16)
    b, cb = drive_random(game, "xla", batches=6, rows_per_batch=2, seed=13,
                         max_prediction=16)
    for t, ((h1, l1), (h2, l2)) in enumerate(zip(ca, cb)):
        np.testing.assert_array_equal(h1, h2, err_msg=f"his batch {t}")
        np.testing.assert_array_equal(l1, l2, err_msg=f"los batch {t}")
    assert_core_equal(a, b)


def test_branchless_depth_variants_bit_parity():
    """Depth-specialized branchless T=1 programs (static nslots variants,
    ResimCore.branchless_variants): every rollback depth must produce
    ring/state/verify/checksums bit-identical to the cond program —
    including rows whose last save sits past the advance count."""
    r = np.random.default_rng(31)
    bl_core = ResimCore(ExGame(P, 256), max_prediction=8, num_players=P,
                        device_verify=True)
    cond_core = ResimCore(ExGame(P, 256), max_prediction=8, num_players=P,
                          device_verify=True)
    assert bl_core._tick_branchless_fn is not None
    cond_fn = cond_core._tick_fn
    W = bl_core.window
    frame = 0
    for t in range(20):
        depth = 0 if frame < 8 else int(r.integers(1, 8))
        do_load = depth > 0
        count = depth + 1 if do_load else 1
        start = frame - depth if do_load else frame
        inputs = np.zeros((W, P, 1), np.uint8)
        statuses = np.zeros((W, P), np.int32)
        for i in range(count):
            inputs[i] = r.integers(0, 16, (P, 1))
        slots = np.full((W,), bl_core.scratch_slot, np.int32)
        for i in range(count + (1 if do_load and count < W else 0)):
            slots[i] = (start + i) % bl_core.ring_len
        row = bl_core.pack_tick_row(
            do_load, (start % bl_core.ring_len) if do_load else 0,
            inputs, statuses, slots, count, start_frame=start,
        )
        ha, la = bl_core.tick_row(row)
        (cond_core.ring, cond_core.state, cond_core.verify, hb, lb) = (
            cond_fn(cond_core.ring, cond_core.state, row, cond_core.verify)
        )
        np.testing.assert_array_equal(np.asarray(ha), np.asarray(hb))
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        frame = start + count
    assert_core_equal(bl_core, cond_core)


def test_pallas_t1_routing_bit_parity():
    """Size-aware T=1 routing (ResimCore.PALLAS_T1_MIN_ENTITIES): on big
    worlds lone ticks dispatch through the pallas tick kernel as a 1-row
    multi instead of the XLA T=1 programs. Lower the threshold on the
    instance so the route engages on a test-sized world, then drive
    LONE ticks (trivial advances AND rollbacks) and require bit-parity
    with the XLA core — ring, state, verify, and returned checksums."""
    r = np.random.default_rng(23)
    games = [ExGame(P, 512) for _ in range(2)]
    pallas = ResimCore(games[0], max_prediction=6, num_players=P,
                       device_verify=True, tick_backend="pallas-interpret")
    pallas.PALLAS_T1_MIN_ENTITIES = 256  # instance override: engage at 512
    assert pallas._pallas_t1()
    xla = ResimCore(games[1], max_prediction=6, num_players=P,
                    device_verify=True, tick_backend="xla")
    W = pallas.window
    frame = 0
    for t in range(14):
        depth = 0 if frame < 6 else int(r.integers(0, 5))
        do_load = depth > 0
        count = depth + 1 if do_load else 1
        start = frame - depth if do_load else frame
        inputs = np.zeros((W, P, 1), np.uint8)
        statuses = np.zeros((W, P), np.int32)
        for i in range(count):
            inputs[i] = r.integers(0, 16, (P, 1))
        slots = np.full((W,), pallas.scratch_slot, np.int32)
        for i in range(count):
            slots[i] = (start + i) % pallas.ring_len
        args = (do_load, (start % pallas.ring_len) if do_load else 0,
                inputs, statuses, slots, count)
        ha, la = pallas.tick(*args, start_frame=start)
        hb, lb = xla.tick(*args, start_frame=start)
        np.testing.assert_array_equal(np.asarray(ha), np.asarray(hb))
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        frame = start + count
    assert_core_equal(pallas, xla)


def test_tick_kernel_multi_row_lazy_parity():
    """The lazy multi-tick buffer through the kernel: a featured backend
    (pallas ticks + lazy batching) vs a plain XLA per-tick backend over
    the same SyncTest stream — states and every save's checksum equal."""

    def make_backend(**kw):
        return TpuRollbackBackend(
            ExGame(P, 256), max_prediction=6, num_players=P, **kw
        )

    def make_sess():
        return (
            SessionBuilder(input_size=1)
            .with_num_players(P)
            .with_max_prediction_window(6)
            .with_check_distance(4)
            .start_synctest_session()
        )

    feat = make_backend(tick_backend="pallas-interpret", lazy_ticks=5)
    plain = make_backend(tick_backend="xla")
    sf, sp = make_sess(), make_sess()
    f_saves, p_saves = [], []
    for t in range(25):
        for h in range(P):
            buf = bytes([(t * (3 + h) + h) % 16])
            sf.add_local_input(h, buf)
            sp.add_local_input(h, buf)
        rf, rp = sf.advance_frame(), sp.advance_frame()
        feat.handle_requests(rf)
        plain.handle_requests(rp)
        f_saves += [
            (r.cell.frame, r.cell.checksum_getter())
            for r in rf
            if hasattr(r, "cell")
        ]
        p_saves += [
            (r.cell.frame, r.cell.checksum_getter())
            for r in rp
            if hasattr(r, "cell")
        ]
    a, b = feat.state_numpy(), plain.state_numpy()
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=k)
    assert len(f_saves) == len(p_saves)
    for (ff, fg), (pf, pg) in zip(f_saves, p_saves):
        assert ff == pf
        assert fg() == pg(), f"checksum frame {ff}"


def test_tick_kernel_requires_disconnect_input():
    """A tileable game without a declared disconnect_input row cannot use
    the kernel explicitly, and auto resolves to xla."""

    class NoDisc(ExGame):
        disconnect_input = None

    with pytest.raises(AssertionError, match="disconnect_input"):
        ResimCore(NoDisc(P, 256), max_prediction=6, num_players=P,
                  tick_backend="pallas-interpret")
    core = ResimCore(NoDisc(P, 256), max_prediction=6, num_players=P,
                     tick_backend="auto")
    assert core.tick_backend == "xla"


@pytest.mark.parametrize("Game,mod", [(ExGame, 16), (Arena, 64)])
def test_branchless_single_tick_bit_parity(Game, mod):
    """The branchless unrolled T=1 program (the interactive path's
    dispatch-overhead fix) must be bit-identical to the cond/scan packed
    program — ring (scratch slot included), state, device-verify carry,
    and per-slot checksums — over random rollback/save/disconnect
    streams."""
    game_a, game_b = Game(P, 256), Game(P, 256)
    a = ResimCore(game_a, max_prediction=6, num_players=P,
                  device_verify=True, tick_backend="xla")
    b = ResimCore(game_b, max_prediction=6, num_players=P,
                  device_verify=True, tick_backend="xla")
    # policy: small world builds the branchless program; the drive below
    # exercises the ROW-CONTENT ROUTING (rollback rows -> branchless,
    # trivial rows -> cond) against a pure-cond twin
    assert a._tick_branchless_fn is not None
    b_fn = jax.jit(b._tick_packed_impl, donate_argnums=(0, 1, 3))

    W = a.window
    r = np.random.default_rng(23)
    frame = 0
    for t in range(18):
        depth = int(r.integers(0, 6))
        do_load = depth > 0 and frame > depth
        count = depth + 1 if do_load else 1
        start = frame - depth if do_load else frame
        inputs = np.zeros((W, P, 1), np.uint8)
        statuses = np.zeros((W, P), np.int32)
        for i in range(count):
            inputs[i] = r.integers(0, mod, (P, 1))
            if r.random() < 0.2:
                statuses[i, r.integers(0, P)] = int(InputStatus.DISCONNECTED)
        slots = np.full((W,), a.scratch_slot, np.int32)
        for i in range(count):
            slots[i] = (start + i) % a.ring_len
        row = a.pack_tick_row(
            do_load, (start % a.ring_len) if do_load else 0, inputs,
            statuses, slots, count, start_frame=start,
        )
        ha, la = a.tick_row(row)
        b.ring, b.state, b.verify, hb, lb = b_fn(
            b.ring, b.state, row, b.verify
        )
        np.testing.assert_array_equal(np.asarray(ha), np.asarray(hb), err_msg=f"his t={t}")
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), err_msg=f"los t={t}")
        frame = start + count
    assert_core_equal(a, b)
