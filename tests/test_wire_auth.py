"""Authenticated transport (ggrs_tpu/network/auth.py): the opt-in MAC
layer that upgrades the tampering threat model the fuzz suite documents —
with tags, in-stream tampering degrades to packet loss, which the
reliability layer absorbs, so full convergence holds even under hostile
byte-flipping (the unauthenticated wire can only promise orderly stalls
or detected desyncs; see tests/test_wire_fuzz.py).
"""

import random

import pytest

from ggrs_tpu import PlayerType, SessionBuilder, SessionState
from ggrs_tpu.native import available
from ggrs_tpu.network.auth import KEY_LEN, AuthenticatedSocket, _ReplayWindow, siphash24
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.utils.clock import FakeClock
from stubs import GameStub

KEY = bytes(range(KEY_LEN))
NATIVE_PARAMS = [False] + ([True] if available() else [])


def test_siphash_reference_vectors():
    """Official SipHash-2-4 test vector (key 000102..0f over 00 01 02 ...):
    the first vectors from the reference implementation's vectors table."""
    expected = [
        0x726FDB47DD0E0E31,
        0x74F839C593DC67FD,
        0x0D6C8009D9A94F5A,
        0x85676696D7FB7E2D,
    ]
    for n, want in enumerate(expected):
        assert siphash24(KEY, bytes(range(n))) == want


@pytest.mark.skipif(not available(), reason="native library not built")
@pytest.mark.parametrize("seed", range(10))
def test_native_siphash_parity(seed):
    from ggrs_tpu import native

    rng = random.Random(seed)
    key = bytes(rng.randrange(256) for _ in range(KEY_LEN))
    data = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 200)))
    assert native.siphash24(key, data) == siphash24(key, data).to_bytes(8, "little")


def build_pair(clock, net, use_native, keys):
    def build(my_addr, other_addr, local_handle, key):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        if use_native:
            b = b.with_native_sessions(True)
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        sock = net.socket(my_addr)
        if key is not None:
            sock = AuthenticatedSocket(sock, key)
        return b.start_p2p_session(sock)

    return build("a", "b", 0, keys[0]), build("b", "a", 1, keys[1])


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
def test_authenticated_pair_converges(use_native):
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=30, jitter_ms=10, loss=0.1, seed=3)
    s0, s1 = build_pair(clock, net, use_native, (KEY, KEY))
    for _ in range(400):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(20)
        if (
            s0.current_state() == SessionState.RUNNING
            and s1.current_state() == SessionState.RUNNING
        ):
            break
    g0, g1 = GameStub(), GameStub()
    for frame in range(50):
        s0.add_local_input(0, bytes([frame % 9]))
        g0.handle_requests(s0.advance_frame())
        s1.add_local_input(1, bytes([(frame * 3) % 9]))
        g1.handle_requests(s1.advance_frame())
        clock.advance(16)
    for _ in range(10):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(16)
    s0.add_local_input(0, b"\x00")
    g0.handle_requests(s0.advance_frame())
    s1.add_local_input(1, b"\x00")
    g1.handle_requests(s1.advance_frame())
    confirmed = min(s0.confirmed_frame(), s1.confirmed_frame())
    assert confirmed > 25
    for f in range(1, confirmed + 1):
        assert g0.history[f] == g1.history[f]


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
def test_key_mismatch_never_synchronizes(use_native):
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    other_key = bytes(KEY_LEN)
    s0, s1 = build_pair(clock, net, use_native, (KEY, other_key))
    for _ in range(100):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(20)
    assert s0.current_state() == SessionState.SYNCHRONIZING
    assert s1.current_state() == SessionState.SYNCHRONIZING
    assert s0.socket.dropped > 0 and s1.socket.dropped > 0


class TamperingNetworkSocket:
    """Flips bits on a fraction of VERIFIED-layer-invisible wire blobs
    (i.e. the tagged datagrams) before the auth wrapper sees them."""

    def __init__(self, inner, rng, rate=0.25):
        self.inner = inner
        self.rng = rng
        self.rate = rate

    def send_wire(self, wire, addr):
        self.inner.send_wire(wire, addr)

    def receive_all_wire(self):
        out = []
        for addr, blob in self.inner.receive_all_wire():
            if self.rng.random() < self.rate and blob:
                b = bytearray(blob)
                b[self.rng.randrange(len(b))] ^= 1 << self.rng.randrange(8)
                blob = bytes(b)
            out.append((addr, blob))
        return out


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
@pytest.mark.parametrize("seed", [2, 9])
def test_tampering_degrades_to_loss_under_auth(use_native, seed):
    """The upgrade over the unauthenticated wire: with MAC tags, every
    bit-flip is rejected before parsing, so in-stream tampering becomes
    plain packet loss — the pair converges with NO divergence and NO
    permanent stall."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=10, seed=seed)

    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        if use_native:
            b = b.with_native_sessions(True)
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        inner = net.socket(my_addr)
        if my_addr == "a":  # one side receives through the tamperer
            inner = TamperingNetworkSocket(inner, random.Random(seed * 131))
        return b.start_p2p_session(AuthenticatedSocket(inner, KEY))

    s0, s1 = build("a", "b", 0), build("b", "a", 1)
    for _ in range(400):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(20)
        if (
            s0.current_state() == SessionState.RUNNING
            and s1.current_state() == SessionState.RUNNING
        ):
            break
    g0, g1 = GameStub(), GameStub()
    for frame in range(60):
        s0.add_local_input(0, bytes([frame % 9]))
        g0.handle_requests(s0.advance_frame())
        s1.add_local_input(1, bytes([(frame * 3) % 9]))
        g1.handle_requests(s1.advance_frame())
        clock.advance(16)
    for _ in range(10):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(16)
    s0.add_local_input(0, b"\x00")
    g0.handle_requests(s0.advance_frame())
    s1.add_local_input(1, b"\x00")
    g1.handle_requests(s1.advance_frame())

    assert s0.socket.dropped > 0, "tamperer never fired"
    confirmed = min(s0.confirmed_frame(), s1.confirmed_frame())
    assert confirmed > 30, f"authenticated pair stalled (confirmed={confirmed})"
    for f in range(1, confirmed + 1):
        assert g0.history[f] == g1.history[f], f"diverged at {f} despite MAC"


# -- replay protection ------------------------------------------------------


def test_replay_window_semantics():
    w = _ReplayWindow()
    assert w.check_and_update(1)
    assert not w.check_and_update(1)  # exact replay
    assert w.check_and_update(5)
    assert w.check_and_update(3)  # in-window reorder accepted once
    assert not w.check_and_update(3)  # ...but only once
    assert w.check_and_update(5 + _ReplayWindow.WINDOW)
    assert not w.check_and_update(5)  # slid out of the window => replay
    # an attacker-influenced u64 jump must not materialize a 2**60-bit mask
    assert w.check_and_update(2**63)
    assert not w.check_and_update(2**63)
    assert w.top == 2**63


class _LoopbackInner:
    """Minimal wire socket: everything sent is received back, with the
    source address chosen per-delivery (for spoofing probes)."""

    def __init__(self):
        self.sent = []
        self._incoming = []

    def send_wire(self, wire, addr):
        self.sent.append(wire)

    def receive_all_wire(self):
        out = self._incoming
        self._incoming = []
        return out

    def deliver(self, addr, blob):
        self._incoming.append((addr, blob))


def _protected_socket(sender_id=None):
    inner = _LoopbackInner()
    return inner, AuthenticatedSocket(inner, KEY, replay_protect=True, sender_id=sender_id)


def test_reflection_of_own_traffic_is_dropped():
    """Capturing a socket's outbound datagram and feeding it back (source
    address spoofed as a peer) must not deliver or poison any window."""
    inner, sock = _protected_socket()
    sock.send_wire(b"hello-wire", "peer")
    blob = inner.sent[0]
    inner.deliver("peer", blob)
    assert sock.receive_all_wire() == []
    assert sock.replayed == 1
    assert not sock._recv_windows  # reflection allocated no window state


def test_spoofed_source_address_cannot_split_replay_state():
    """Windows key on the authenticated sender id, not the UDP source
    address: the same captured datagram replayed from N spoofed addresses
    is accepted once and rejected N times, with exactly one window."""
    _, sender = _protected_socket(sender_id=b"AAAAAAAA")
    inner_r, receiver = _protected_socket(sender_id=b"BBBBBBBB")
    sender.inner.sent.clear()
    sender.send_wire(b"payload", "r")
    blob = sender.inner.sent[0]
    inner_r.deliver("addr0", blob)
    assert [w for _, w in receiver.receive_all_wire()] == [b"payload"]
    for i in range(5):
        inner_r.deliver(f"spoofed{i}", blob)
    assert receiver.receive_all_wire() == []
    assert receiver.replayed == 5
    assert len(receiver._recv_windows) == 1


def test_mode_splice_rejected():
    """A plain-mode packet must not be splicable into a protected receiver
    by byte-stripping: the two modes use distinct equal-length MAC domains,
    so any cross-mode delivery fails tag verification."""
    plain_inner = _LoopbackInner()
    plain = AuthenticatedSocket(plain_inner, KEY)
    # craft a plain packet whose wire STARTS with the protected domain byte
    plain.send_wire(b"\x01" + bytes(range(24)), "x")
    blob = plain_inner.sent[0]
    inner_r, receiver = _protected_socket()
    for attempt in (blob, blob[1:]):  # as-is, and domain-byte-stripped
        inner_r.deliver("p", attempt)
        assert receiver.receive_all_wire() == []
    assert receiver.dropped == 2
    assert not receiver._recv_windows


class ReplayingSocket:
    """On-path replay attacker: records every received datagram and
    re-delivers each one a second time on the next receive call."""

    def __init__(self, inner):
        self.inner = inner
        self._pending = []

    def send_wire(self, wire, addr):
        self.inner.send_wire(wire, addr)

    def receive_all_wire(self):
        out = list(self._pending)
        self._pending = []
        fresh = self.inner.receive_all_wire()
        self._pending.extend(fresh)
        out.extend(fresh)
        return out


def test_replay_protect_drops_duplicates_and_converges():
    """With replay_protect, a 2× duplication attack costs nothing: every
    duplicate is rejected by the window (counted in .replayed) and the pair
    still converges with identical histories."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=10, seed=7)

    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        inner = net.socket(my_addr)
        if my_addr == "a":  # one side receives through the replayer
            inner = ReplayingSocket(inner)
        return b.start_p2p_session(
            AuthenticatedSocket(inner, KEY, replay_protect=True)
        )

    s0, s1 = build("a", "b", 0), build("b", "a", 1)
    for _ in range(400):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(20)
        if (
            s0.current_state() == SessionState.RUNNING
            and s1.current_state() == SessionState.RUNNING
        ):
            break
    g0, g1 = GameStub(), GameStub()
    for frame in range(50):
        s0.add_local_input(0, bytes([frame % 9]))
        g0.handle_requests(s0.advance_frame())
        s1.add_local_input(1, bytes([(frame * 3) % 9]))
        g1.handle_requests(s1.advance_frame())
        clock.advance(16)
    for _ in range(10):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(16)
    s0.add_local_input(0, b"\x00")
    g0.handle_requests(s0.advance_frame())
    s1.add_local_input(1, b"\x00")
    g1.handle_requests(s1.advance_frame())

    assert s0.socket.replayed > 0, "replayer never fired"
    confirmed = min(s0.confirmed_frame(), s1.confirmed_frame())
    assert confirmed > 25, f"replay-protected pair stalled (confirmed={confirmed})"
    for f in range(1, confirmed + 1):
        assert g0.history[f] == g1.history[f]


def test_replay_protect_mismatch_never_synchronizes():
    """Counter framing is under the MAC, so a protected peer and an
    unprotected peer see each other's packets as unauthenticated."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)

    def build(my_addr, other_addr, local_handle, protect):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        return b.start_p2p_session(
            AuthenticatedSocket(net.socket(my_addr), KEY, replay_protect=protect)
        )

    s0 = build("a", "b", 0, True)
    s1 = build("b", "a", 1, False)
    for _ in range(100):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        clock.advance(20)
    assert s0.current_state() == SessionState.SYNCHRONIZING
    assert s1.current_state() == SessionState.SYNCHRONIZING
    assert s0.socket.dropped > 0 and s1.socket.dropped > 0
