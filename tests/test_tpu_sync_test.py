"""Fully-fused device SyncTest vs the host session + backend pair."""

import numpy as np
import pytest

from ggrs_tpu import MismatchedChecksum, SessionBuilder
from ggrs_tpu.models import ex_game

PLAYERS = 2
ENTITIES = 128


def scripted(frames):
    rng = np.random.default_rng(17)
    return rng.integers(0, 16, size=(frames, PLAYERS, 1), dtype=np.uint8)


@pytest.mark.parametrize("input_delay", [0, 2])
def test_fused_session_matches_host_path(input_delay):
    from ggrs_tpu.tpu import TpuRollbackBackend
    from ggrs_tpu.tpu.sync_test import TpuSyncTestSession

    frames = 90
    check_distance = 7
    inputs = scripted(frames)

    # host path: SyncTestSession emitting requests, fused per-tick backend
    host_sess = (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(8)
        .with_check_distance(check_distance)
        .with_input_delay(input_delay)
        .start_synctest_session()
    )
    backend = TpuRollbackBackend(
        ex_game.ExGame(PLAYERS, ENTITIES), max_prediction=8, num_players=PLAYERS
    )
    for f in range(frames):
        for h in range(PLAYERS):
            host_sess.add_local_input(h, bytes(inputs[f, h]))
        backend.handle_requests(host_sess.advance_frame())

    # fused path: whole batches per dispatch
    fused = TpuSyncTestSession(
        ex_game.ExGame(PLAYERS, ENTITIES),
        num_players=PLAYERS,
        check_distance=check_distance,
        input_delay=input_delay,
        flush_interval=30,
    )
    fused.advance_frames(inputs[:40])
    fused.advance_frames(inputs[40:])
    fused.check()

    a = backend.state_numpy()
    b = fused.state_numpy()
    for key in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(np.asarray(a[key]), np.asarray(b[key]))


def test_fused_session_detects_ring_corruption():
    import jax

    from ggrs_tpu.tpu.sync_test import TpuSyncTestSession

    fused = TpuSyncTestSession(
        ex_game.ExGame(PLAYERS, 64),
        num_players=PLAYERS,
        check_distance=4,
        flush_interval=1000,  # manual check()
    )
    inputs = scripted(80)
    fused.advance_frames(inputs[:40])
    fused.check()  # clean so far

    # corrupt a snapshot the next rollback will load
    slot = (fused.current_frame - 4) % fused.ring_len
    fused.carry = dict(fused.carry)
    fused.carry["ring"] = dict(fused.carry["ring"])
    fused.carry["ring"]["pos"] = fused.carry["ring"]["pos"].at[slot, 0, 0].add(3)

    fused.advance_frames(inputs[40:])
    with pytest.raises(MismatchedChecksum):
        fused.check()
