"""Durable checkpoint/resume: a resumed session must continue bit-exactly."""

import numpy as np

from ggrs_tpu.models import ex_game

PLAYERS = 2
ENTITIES = 64


def scripted(frames, seed=23):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 16, size=(frames, PLAYERS, 1), dtype=np.uint8)


def test_roundtrip_flatten(tmp_path):
    from ggrs_tpu.utils.checkpoint import (
        load_device_checkpoint,
        save_device_checkpoint,
    )

    tree = {
        "a": np.arange(6, dtype=np.int32).reshape(2, 3),
        "nested": {"x": np.zeros((), np.uint32), "y": np.ones(4, np.uint8)},
    }
    path = str(tmp_path / "ck.npz")
    save_device_checkpoint(path, tree, {"n": 42, "s": "hi"})
    got, meta = load_device_checkpoint(path)
    assert meta == {"n": 42, "s": "hi"}
    np.testing.assert_array_equal(got["a"], tree["a"])
    np.testing.assert_array_equal(got["nested"]["y"], tree["nested"]["y"])


def test_fused_session_resume_bitexact(tmp_path):
    from ggrs_tpu.tpu.sync_test import TpuSyncTestSession

    inputs = scripted(90)
    game = ex_game.ExGame(PLAYERS, ENTITIES)

    straight = TpuSyncTestSession(game, PLAYERS, check_distance=5, input_delay=2)
    straight.advance_frames(inputs)

    resumed = TpuSyncTestSession(game, PLAYERS, check_distance=5, input_delay=2)
    resumed.advance_frames(inputs[:50])
    path = str(tmp_path / "sess.npz")
    resumed.save(path)

    # a fresh process would do exactly this: rebuild the game, restore, go on
    back = TpuSyncTestSession.restore(path, ex_game.ExGame(PLAYERS, ENTITIES))
    assert back.current_frame == 50
    back.advance_frames(inputs[50:])
    back.check()

    a = straight.state_numpy()
    b = back.state_numpy()
    for key in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(np.asarray(a[key]), np.asarray(b[key]))


def test_backend_resume_bitexact(tmp_path):
    from ggrs_tpu import SessionBuilder
    from ggrs_tpu.tpu import TpuRollbackBackend

    inputs = scripted(60, seed=7)

    def drive(handler, sess, lo, hi):
        for f in range(lo, hi):
            for h in range(PLAYERS):
                sess.add_local_input(h, bytes(inputs[f, h]))
            handler.handle_requests(sess.advance_frame())

    def new_sess():
        return (
            SessionBuilder(input_size=1)
            .with_num_players(PLAYERS)
            .with_max_prediction_window(8)
            .with_check_distance(4)
            .start_synctest_session()
        )

    game = ex_game.ExGame(PLAYERS, ENTITIES)
    straight = TpuRollbackBackend(game, max_prediction=8, num_players=PLAYERS)
    s1 = new_sess()
    drive(straight, s1, 0, 60)

    first = TpuRollbackBackend(game, max_prediction=8, num_players=PLAYERS)
    s2 = new_sess()
    drive(first, s2, 0, 35)
    path = str(tmp_path / "backend.npz")
    first.save(path)

    # NB: the session's host-side queues aren't part of the device
    # checkpoint; resuming mid-session means resuming the session object too.
    # Here the same session object continues against a restored backend —
    # the device state must be bit-identical to never-checkpointed.
    back = TpuRollbackBackend.restore(path, ex_game.ExGame(PLAYERS, ENTITIES))
    assert back.current_frame == 35
    drive(back, s2, 35, 60)

    a = straight.state_numpy()
    b = back.state_numpy()
    for key in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(np.asarray(a[key]), np.asarray(b[key]))


def test_backend_device_verify_survives_restore(tmp_path):
    """A checkpointed device-verify run resumes with its accumulated
    first-seen history AND its latch: a divergence injected before the
    save is still reported after restore, and check() works at all
    (ADVICE r2: restore used to drop device_verify silently)."""
    import pytest

    from ggrs_tpu import SessionBuilder
    from ggrs_tpu.errors import MismatchedChecksum
    from ggrs_tpu.tpu import TpuRollbackBackend

    inputs = scripted(40, seed=9)
    game = ex_game.ExGame(PLAYERS, ENTITIES)
    backend = TpuRollbackBackend(
        game, max_prediction=8, num_players=PLAYERS, device_verify=True
    )
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(8)
        .with_check_distance(4)
        .with_device_checksum_verification()  # the device latch is the referee
        .start_synctest_session()
    )
    for f in range(20):
        for h in range(PLAYERS):
            sess.add_local_input(h, bytes(inputs[f, h]))
        backend.handle_requests(sess.advance_frame())
    backend.check()  # clean so far
    # corrupt a saved ring slot: the NEXT re-save of that frame must differ
    slot = (backend.current_frame - 4) % backend.core.ring_len
    backend.core.ring["pos"] = backend.core.ring["pos"].at[slot, 0, 0].add(7)
    for f in range(20, 26):
        for h in range(PLAYERS):
            sess.add_local_input(h, bytes(inputs[f, h]))
        backend.handle_requests(sess.advance_frame())

    path = str(tmp_path / "dv.npz")
    backend.save(path)
    restored = TpuRollbackBackend.restore(path, ex_game.ExGame(PLAYERS, ENTITIES))
    assert restored.core.device_verify, "device_verify lost in restore"
    with pytest.raises(MismatchedChecksum):
        restored.check()


def test_fused_resume_across_backends(tmp_path):
    """Checkpoints are backend-agnostic: a run saved under the XLA scan
    resumes bit-exactly under the tiled pallas kernel and vice versa."""
    import numpy as np

    import jax
    import jax.tree_util as jtu

    from ggrs_tpu.models.ex_game import ExGame
    from ggrs_tpu.tpu import TpuSyncTestSession

    rng = np.random.default_rng(3)
    script = rng.integers(0, 16, size=(24, 2, 1), dtype=np.uint8)
    sess = TpuSyncTestSession(
        ExGame(2, 1024), num_players=2, check_distance=3, backend="xla"
    )
    sess.advance_frames(script[:12])
    path = str(tmp_path / "xb.npz")
    sess.save(path)

    resumed = {}
    for backend in ("xla", "pallas-tiled-interpret"):
        r = TpuSyncTestSession.restore(
            path, ExGame(2, 1024), backend=backend
        )
        r.advance_frames(script[12:])
        r.check()
        resumed[backend] = jax.device_get(r.carry)
    la = jtu.tree_leaves_with_path(resumed["xla"])
    lb = jtu.tree_leaves(resumed["pallas-tiled-interpret"])
    for (p, x), y in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=jtu.keystr(p)
        )


def test_backend_restore_preserves_performance_knobs(tmp_path):
    """A restored backend must run with the performance characteristics
    of the session that saved it (r3 advisor): lazy_ticks, the
    speculation gate, defer_speculation, and an explicit xla backend
    choice all round-trip through the checkpoint meta (pallas choices
    re-resolve via auto so a cross-platform restore cannot crash)."""
    from ggrs_tpu.tpu import TpuRollbackBackend

    game = ex_game.ExGame(PLAYERS, ENTITIES)
    backend = TpuRollbackBackend(
        game,
        max_prediction=6,
        num_players=PLAYERS,
        beam_width=4,
        lazy_ticks=5,
        speculation_gate="adaptive",
        defer_speculation=True,
        tick_backend="xla",
    )
    path = str(tmp_path / "knobs.npz")
    backend.save(path)

    restored = TpuRollbackBackend.restore(
        path, ex_game.ExGame(PLAYERS, ENTITIES)
    )
    assert restored.lazy_ticks == 5
    assert restored.speculation_gate == "adaptive"
    assert restored.defer_speculation is True
    assert restored.beam_width == 4
    assert restored.core.tick_backend == "xla"

    # pre-knob checkpoints (no fields in meta) restore with defaults
    from ggrs_tpu.utils.checkpoint import (
        load_device_checkpoint,
        save_device_checkpoint,
    )

    tree, meta = load_device_checkpoint(path)
    for key in ("lazy_ticks", "speculation_gate", "defer_speculation",
                "spec_backend", "tick_backend"):
        meta.pop(key)
    old_path = str(tmp_path / "old.npz")
    save_device_checkpoint(old_path, tree, meta)
    legacy = TpuRollbackBackend.restore(
        old_path, ex_game.ExGame(PLAYERS, ENTITIES)
    )
    assert legacy.lazy_ticks == 0
    assert legacy.speculation_gate == "always"


# ----------------------------------------------------------------------
# format version + payload manifest (fleet-operations hardening): a
# damaged or foreign checkpoint must fail AT THE DOOR with the typed
# CheckpointIncompatible, never as a shape error deep inside a restore
# ----------------------------------------------------------------------


def _small_checkpoint(tmp_path, name="fmt.npz"):
    from ggrs_tpu.utils.checkpoint import save_device_checkpoint

    tree = {
        "rings": {"pos": np.arange(12, dtype=np.int32).reshape(3, 4)},
        "states": {"pos": np.ones((4,), np.uint32)},
    }
    path = str(tmp_path / name)
    save_device_checkpoint(path, tree, {"kind": "test", "n": 1})
    return path, tree


def test_checkpoint_stamps_version_and_manifest(tmp_path):
    import json

    from ggrs_tpu.utils.checkpoint import (
        CHECKPOINT_FORMAT_VERSION,
        load_device_checkpoint,
    )

    path, tree = _small_checkpoint(tmp_path)
    with np.load(path) as data:  # raw read: the stamp is in the file...
        meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
    fmt = meta["__format__"]
    assert fmt["version"] == CHECKPOINT_FORMAT_VERSION
    assert set(fmt["manifest"]) == {"t/rings/pos", "t/states/pos"}
    # ...and the stamp is INTERNAL: callers' meta round-trips unchanged
    got, meta_back = load_device_checkpoint(path)
    assert meta_back == {"kind": "test", "n": 1}
    np.testing.assert_array_equal(got["rings"]["pos"], tree["rings"]["pos"])


def test_checkpoint_truncated_file_raises_typed(tmp_path):
    import pytest

    from ggrs_tpu.errors import CheckpointIncompatible
    from ggrs_tpu.utils.checkpoint import load_device_checkpoint

    path, _ = _small_checkpoint(tmp_path)
    blob = open(path, "rb").read()
    for cut in (len(blob) // 2, 10):
        with open(path, "wb") as f:
            f.write(blob[:cut])
        with pytest.raises(CheckpointIncompatible):
            load_device_checkpoint(path)


def test_checkpoint_future_version_raises_with_both_versions(tmp_path):
    import pytest

    from ggrs_tpu.errors import CheckpointIncompatible
    from ggrs_tpu.utils import checkpoint as ck

    path, tree = _small_checkpoint(tmp_path)
    orig = ck.CHECKPOINT_FORMAT_VERSION
    try:
        ck.CHECKPOINT_FORMAT_VERSION = orig + 5  # "a newer build wrote it"
        ck.save_device_checkpoint(path, tree, {"kind": "test"})
    finally:
        ck.CHECKPOINT_FORMAT_VERSION = orig
    with pytest.raises(CheckpointIncompatible) as exc_info:
        ck.load_device_checkpoint(path)
    assert exc_info.value.found == orig + 5
    assert exc_info.value.expected == orig


def test_checkpoint_manifest_catches_missing_payload(tmp_path):
    import os
    import pytest
    import zipfile

    from ggrs_tpu.errors import CheckpointIncompatible
    from ggrs_tpu.utils.checkpoint import load_device_checkpoint

    path, _ = _small_checkpoint(tmp_path)
    clipped = str(tmp_path / "clipped.npz")
    with zipfile.ZipFile(path) as src, zipfile.ZipFile(clipped, "w") as dst:
        for item in src.infolist():
            if item.filename != "t/states/pos.npy":  # drop one payload
                dst.writestr(item, src.read(item.filename))
    with pytest.raises(CheckpointIncompatible) as exc_info:
        load_device_checkpoint(clipped)
    assert exc_info.value.expected == "t/states/pos"
    os.remove(clipped)


def test_checkpoint_legacy_unstamped_still_loads(tmp_path):
    """Pre-version checkpoints (no __format__ in meta) load best-effort:
    the stamp is additive, old files on disk stay restorable."""
    import json

    from ggrs_tpu.utils.checkpoint import load_device_checkpoint

    path = str(tmp_path / "legacy.npz")
    flat = {
        "t/a": np.arange(3, dtype=np.int32),
        "__meta__": np.frombuffer(
            json.dumps({"kind": "old"}).encode(), dtype=np.uint8
        ),
    }
    np.savez_compressed(path, **flat)
    tree, meta = load_device_checkpoint(path)
    assert meta == {"kind": "old"}
    np.testing.assert_array_equal(tree["a"], np.arange(3, dtype=np.int32))


def test_atomic_write_failure_leaves_previous_file_intact(tmp_path):
    """A write that dies mid-flight (the SIGKILL-shaped failure) must
    leave the PREVIOUS complete file at the path and no visible torn
    file — os.replace is the commit point, everything before it is
    invisible."""
    import os

    import pytest

    from ggrs_tpu.utils.checkpoint import atomic_write_bytes

    path = str(tmp_path / "state.bin")
    atomic_write_bytes(path, b"v1" * 1000)

    real_replace = os.replace

    def dying_replace(src, dst):
        raise OSError("simulated death at the commit point")

    os.replace = dying_replace
    try:
        with pytest.raises(OSError):
            atomic_write_bytes(path, b"v2" * 1000)
    finally:
        os.replace = real_replace
    with open(path, "rb") as f:
        assert f.read() == b"v1" * 1000
    leftovers = [p for p in os.listdir(tmp_path) if ".tmp." in p]
    assert leftovers == []  # the temp file was cleaned up


def test_save_device_checkpoint_crash_mid_write_keeps_old_checkpoint(
    tmp_path, monkeypatch
):
    """save_device_checkpoint dying mid-serialization must not touch the
    checkpoint already on disk: the old file still loads, bit-exact."""
    import numpy as _np
    import pytest

    from ggrs_tpu.utils import checkpoint as ckpt

    path = str(tmp_path / "host.npz")
    tree = {"a": np.arange(8, dtype=np.int32)}
    ckpt.save_device_checkpoint(path, tree, {"kind": "t"})

    def dying_savez(buf, **arrays):
        buf.write(b"PK\x03\x04partial")  # a torn zip prefix
        raise RuntimeError("killed mid-write")

    monkeypatch.setattr(_np, "savez_compressed", dying_savez)
    with pytest.raises(RuntimeError):
        ckpt.save_device_checkpoint(
            path, {"a": np.arange(8, dtype=np.int32) + 1}, {"kind": "t"}
        )
    monkeypatch.undo()
    loaded, meta = ckpt.load_device_checkpoint(path)
    np.testing.assert_array_equal(loaded["a"], tree["a"])
    assert meta == {"kind": "t"}


def test_atomic_write_survives_real_sigkill_mid_write(tmp_path):
    """The real thing: a child process SIGKILLed while overwriting the
    same path in a tight loop can never leave a torn file — every
    observation is one COMPLETE payload (old or new)."""
    import os
    import signal
    import subprocess
    import sys
    import time

    path = str(tmp_path / "hammer.bin")
    child = subprocess.Popen([
        sys.executable, "-c",
        "import sys; sys.path.insert(0, %r)\n"
        "from ggrs_tpu.utils.checkpoint import atomic_write_bytes\n"
        "i = 0\n"
        "while True:\n"
        "    payload = bytes([i %% 256]) * 65536\n"
        "    atomic_write_bytes(%r, payload, durable=False)\n"
        "    i += 1\n"
        % (os.getcwd(), path),
    ], cwd=os.getcwd())
    try:
        deadline = time.monotonic() + 10
        while not os.path.exists(path):
            assert child.poll() is None, "writer died before first write"
            assert time.monotonic() < deadline, "writer never wrote"
            time.sleep(0.01)
        time.sleep(0.25)  # let it hammer through many replace cycles
    finally:
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=10)
    with open(path, "rb") as f:
        data = f.read()
    assert len(data) == 65536  # complete payload, never a torn prefix
    assert data == bytes([data[0]]) * 65536
