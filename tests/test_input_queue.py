"""InputQueue behavior (parity with reference in-module tests,
src/input_queue.rs:246-327)."""

import pytest

from ggrs_tpu.frame_info import PlayerInput
from ggrs_tpu.input_queue import InputQueue
from ggrs_tpu.types import NULL_FRAME, InputStatus


def inp(frame, b):
    return PlayerInput(frame, bytes([b]))


def test_add_input_wrong_frame():
    q = InputQueue(1)
    q.add_input(inp(0, 0))
    with pytest.raises(AssertionError):
        q.add_input(inp(3, 0))  # not sequential


def test_add_input_twice():
    q = InputQueue(1)
    q.add_input(inp(0, 0))
    with pytest.raises(AssertionError):
        q.add_input(inp(0, 0))


def test_add_input_sequentially():
    q = InputQueue(1)
    for i in range(10):
        q.add_input(inp(i, 0))
        assert q.last_added_frame == i
        assert q.length == i + 1


def test_input_sequentially():
    q = InputQueue(1)
    for i in range(10):
        q.add_input(inp(i, i))
        buf, status = q.input(i)
        assert status == InputStatus.CONFIRMED
        assert buf[0] == i


def test_delayed_inputs():
    q = InputQueue(1)
    delay = 2
    q.set_frame_delay(delay)
    for i in range(10):
        q.add_input(inp(i, i))
        assert q.last_added_frame == i + delay
        assert q.length == i + delay + 1
        buf, _status = q.input(i)
        assert buf[0] == max(0, i - delay)


def test_prediction_and_misprediction_detection():
    q = InputQueue(1)
    q.add_input(inp(0, 7))
    # request beyond what's confirmed -> repeat-last prediction
    buf, status = q.input(1)
    assert status == InputStatus.PREDICTED
    assert buf[0] == 7
    buf, status = q.input(2)
    assert status == InputStatus.PREDICTED
    # real input for frame 1 disagrees with the prediction
    q.add_input(inp(1, 9))
    assert q.first_incorrect_frame == 1


def test_prediction_correct_exits_prediction_mode():
    q = InputQueue(1)
    q.add_input(inp(0, 7))
    q.input(1)  # predict 7
    q.add_input(inp(1, 7))  # matches; caught up with last request
    assert q.first_incorrect_frame == NULL_FRAME
    buf, status = q.input(1)
    assert status == InputStatus.CONFIRMED
    assert buf[0] == 7


def test_discard_confirmed_frames():
    q = InputQueue(1)
    for i in range(10):
        q.add_input(inp(i, i))
    q.input(9)
    q.discard_confirmed_frames(5)
    assert q.length == 5  # frames 5..9 remain
    buf, status = q.input(9)
    assert status == InputStatus.CONFIRMED and buf[0] == 9
