"""Multi-chip sharding on the virtual 8-device CPU mesh: entity-sharded
state, beam-sharded speculation, psum checksum parity."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax_mod():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax


def test_mesh_shapes(jax_mod):
    from ggrs_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    assert mesh.axis_names == ("beam", "entity")
    assert mesh.devices.shape == (2, 4)
    mesh1 = make_mesh(1)
    assert mesh1.devices.shape == (1, 1)


def test_sharded_checksum_matches_single_device(jax_mod):
    jax = jax_mod
    from ggrs_tpu.models import ex_game
    from ggrs_tpu.parallel.mesh import make_mesh
    from ggrs_tpu.parallel.sharded import shard_state, sharded_checksum

    mesh = make_mesh(8)
    n_entities = 1024  # divisible by the 4-way entity axis

    game = ex_game.ExGame(num_players=2, num_entities=n_entities)
    host_state = ex_game.init_oracle(num_players=2, num_entities=n_entities)

    sharded = shard_state(jax.device_put(host_state), mesh)
    hi, lo = sharded_checksum(sharded, mesh)
    # bit-identical to the single-device/oracle checksum: a sharded peer and
    # a single-chip peer must agree on desync-detection reports
    ohi, olo = ex_game.checksum_oracle(host_state)
    assert int(hi) == ohi
    assert int(lo) == olo


def test_sharded_beam_rollout_matches_oracle(jax_mod):
    jax = jax_mod
    from ggrs_tpu.models import ex_game
    from ggrs_tpu.parallel.mesh import make_mesh
    from ggrs_tpu.parallel.sharded import make_sharded_beam_rollout, shard_state

    mesh = make_mesh(8)
    n_entities, players, window, beam = 512, 2, 4, 4
    game = ex_game.ExGame(num_players=players, num_entities=n_entities)
    host_state = ex_game.init_oracle(num_players=players, num_entities=n_entities)

    rng = np.random.default_rng(5)
    beam_inputs = rng.integers(0, 16, size=(beam, window, players, 1), dtype=np.uint8)
    beam_statuses = np.zeros((beam, window, players), dtype=np.int32)

    run = make_sharded_beam_rollout(game, mesh, window)
    state = shard_state(jax.device_put(host_state), mesh)
    finals, hi, lo = run(state, beam_inputs, beam_statuses)

    # oracle: each beam member independently
    for b in range(beam):
        s = {k: np.copy(v) for k, v in host_state.items()}
        for w in range(window):
            s = ex_game.step_oracle(s, beam_inputs[b, w], beam_statuses[b, w], players)
        got = jax.device_get(jax.tree.map(lambda x: x[b], finals))
        for key in ("frame", "pos", "vel", "rot"):
            np.testing.assert_array_equal(np.asarray(got[key]), s[key])
        ohi, olo = ex_game.checksum_oracle(s)
        assert int(hi[b]) == ohi and int(lo[b]) == olo


def test_sharded_fused_synctest_64k_16frame(jax_mod):
    """BASELINE configs[4]: 64k-component ECS state, 16-frame rollback,
    entity-sharded over the mesh — bit-identical to the unsharded session."""
    jax = jax_mod
    import numpy as np

    from ggrs_tpu.models import ex_game
    from ggrs_tpu.parallel.mesh import make_mesh
    from ggrs_tpu.tpu.sync_test import TpuSyncTestSession

    players = 4
    entities = 65536 // 5  # ~64k int32 components (5 words per entity)
    entities -= entities % 4  # divisible by the 4-way entity axis
    frames = 40
    rng = np.random.default_rng(31)
    inputs = rng.integers(0, 16, size=(frames, players, 1), dtype=np.uint8)

    mesh = make_mesh(8)
    sharded = TpuSyncTestSession(
        ex_game.ExGame(players, entities),
        num_players=players,
        check_distance=16,
        mesh=mesh,
        flush_interval=1000,
    )
    sharded.advance_frames(inputs)
    sharded.check()

    plain = TpuSyncTestSession(
        ex_game.ExGame(players, entities),
        num_players=players,
        check_distance=16,
        flush_interval=1000,
    )
    plain.advance_frames(inputs)
    plain.check()

    a = sharded.state_numpy()
    b = plain.state_numpy()
    for key in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(np.asarray(a[key]), np.asarray(b[key]))
