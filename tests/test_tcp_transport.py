"""Third transport witness (VERDICT r1 item 8): P2P sessions over the
TCP-backed datagram socket — the seam the reference ecosystem uses to swap
in WebRTC (README.md:50-55). Same session code, different L1."""

import time

import pytest

from ggrs_tpu import PlayerType, SessionBuilder, SessionState
from ggrs_tpu.network.tcp_socket import TcpDatagramSocket
from stubs import GameStub

KEY = bytes(range(16, 32))


def build_pair(port_a, port_b, auth=False):
    def build(my_port, other_port, handle):
        sock = TcpDatagramSocket(my_port)
        if auth:
            from ggrs_tpu.network.auth import AuthenticatedSocket

            sock = AuthenticatedSocket(sock, KEY)
        return (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(8)
            .add_player(PlayerType.local(), handle)
            .add_player(PlayerType.remote(("127.0.0.1", other_port)), 1 - handle)
            .start_p2p_session(sock)
        )

    return build(port_a, port_b, 0), build(port_b, port_a, 1)


def run_lockstep(s0, s1, frames):
    for _ in range(300):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        s0.events()
        s1.events()
        if (
            s0.current_state() == SessionState.RUNNING
            and s1.current_state() == SessionState.RUNNING
        ):
            break
        time.sleep(0.002)
    assert s0.current_state() == SessionState.RUNNING, "TCP handshake failed"

    g0, g1 = GameStub(), GameStub()
    for f in range(frames):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        s0.add_local_input(0, bytes([f % 11]))
        s1.add_local_input(1, bytes([(f * 3 + 1) % 11]))
        g0.handle_requests(s0.advance_frame())
        g1.handle_requests(s1.advance_frame())
        if f % 8 == 0:
            time.sleep(0.001)
    for _ in range(30):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        time.sleep(0.001)
    s0.add_local_input(0, b"\x00")
    g0.handle_requests(s0.advance_frame())
    s1.add_local_input(1, b"\x00")
    g1.handle_requests(s1.advance_frame())

    confirmed = min(s0.confirmed_frame(), s1.confirmed_frame())
    assert confirmed > frames // 2
    for f in range(1, confirmed + 1):
        assert g0.history[f] == g1.history[f], f"diverged at frame {f}"


def test_p2p_over_tcp_transport():
    s0, s1 = build_pair(17951, 17952)
    run_lockstep(s0, s1, frames=80)


def test_p2p_over_tcp_with_authenticated_wrapper():
    """The MAC wrapper composes over any wire-level transport."""
    s0, s1 = build_pair(17953, 17954, auth=True)
    run_lockstep(s0, s1, frames=60)


def test_tcp_socket_wire_roundtrip():
    a, b = TcpDatagramSocket(17955), TcpDatagramSocket(17956)
    a.send_wire(b"hello-wire", ("127.0.0.1", 17956))
    got = []
    for _ in range(100):
        got = b.receive_all_wire()
        if got:
            break
        a.receive_all_wire()  # drains a's pending connect/flush
        time.sleep(0.002)
    assert got and got[0] == (("127.0.0.1", 17955), b"hello-wire")
    # reply flows back over the canonical address without a fresh dial
    b.send_wire(b"pong", got[0][0])
    back = []
    for _ in range(100):
        back = a.receive_all_wire()
        if back:
            break
        b.receive_all_wire()
        time.sleep(0.002)
    assert back and back[0][1] == b"pong"
    a.close()
    b.close()


def test_p2p_over_tcp_with_hostname_addresses():
    """Sessions configured with a hostname ('localhost') instead of a
    numeric IP: inbound attribution must echo the CONFIGURED address form
    or every received message silently misses the endpoint route."""

    def build(my_port, other_port, handle):
        return (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(8)
            .add_player(PlayerType.local(), handle)
            .add_player(PlayerType.remote(("localhost", other_port)), 1 - handle)
            .start_p2p_session(TcpDatagramSocket(my_port))
        )

    s0, s1 = build(17959, 17960, 0), build(17960, 17959, 1)
    run_lockstep(s0, s1, frames=40)


def test_dead_stream_is_datagram_loss_not_crash():
    a = TcpDatagramSocket(17957)
    # nobody listens on 17958: the dialed stream dies; sends must neither
    # block nor raise (loss is the seam's contract)
    for _ in range(5):
        a.send_wire(b"x", ("127.0.0.1", 17958))
        a.receive_all_wire()
        time.sleep(0.002)
    a.close()


def test_native_session_over_tcp_transport():
    """The native C++ session core pumps through the Python socket seam,
    so it composes with the TCP transport unchanged — full-native peer vs
    Python peer over TCP streams."""
    from ggrs_tpu.native import available

    if not available():
        pytest.skip("native library not built")

    def build(my_port, other_port, handle, native):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(8)
            .add_player(PlayerType.local(), handle)
            .add_player(PlayerType.remote(("127.0.0.1", other_port)), 1 - handle)
        )
        if native:
            b = b.with_native_sessions(True)
        return b.start_p2p_session(TcpDatagramSocket(my_port))

    s0 = build(17961, 17962, 0, native=False)
    s1 = build(17962, 17961, 1, native=True)
    run_lockstep(s0, s1, frames=60)


def test_dead_connection_invalidates_dns_cache():
    """A hostname whose cached resolution points at a dead stream is
    re-resolved on the next send (DNS failover / container restart with a
    new IP — r3 advisor): after the stale conn dies, traffic to the
    hostname reaches the peer at its CURRENT address instead of
    blackholing for the socket's lifetime."""
    import socket as _socket

    from ggrs_tpu.network.tcp_socket import TcpDatagramSocket, _Conn

    a = TcpDatagramSocket(0, host="127.0.0.1")
    b = TcpDatagramSocket(0, host="127.0.0.1")
    try:
        port = b.local_port
        # poison the cache: 'localhost' resolved to a stale address whose
        # stream is already dead (the failed-over old IP)
        a._resolved["localhost"] = "192.0.2.1"  # TEST-NET, unroutable
        stale = _Conn(
            _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM),
            ("192.0.2.1", port),
        )
        stale.dead = True
        a._conns[("192.0.2.1", port)] = stale

        # the REAL route: the session's regular receive poll reaps the
        # dead conn AND drops the hostname's stale resolution with it —
        # without that, send_wire would find no conn at all and reconnect
        # to the cached stale IP forever
        a.receive_all_wire()
        assert ("192.0.2.1", port) not in a._conns
        assert "localhost" not in a._resolved

        a.send_wire(b"\x07failover", ("localhost", port))
        # re-resolution must have replaced the cache entry
        assert a._resolved["localhost"] == "127.0.0.1"
        got = []
        for _ in range(400):
            a.receive_all_wire()  # drives flushes/accepts on a's side too
            got = b.receive_all_wire()
            if got:
                break
            time.sleep(0.005)
        assert got, "message never arrived after DNS-cache invalidation"
        assert got[0][1] == b"\x07failover"
    finally:
        a.close()
        b.close()
