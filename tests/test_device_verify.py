"""On-device SyncTest verification in the request-path backend: the
first-seen checksum history and mismatch verdict live on device, so a
determinism run makes ZERO per-burst checksum readbacks (the tunneled
device charges ~100ms per readback — the dominant cost of the interactive
path before this). Semantics mirror the fused session's _save_and_check /
the reference comparison (src/sessions/sync_test_session.rs:85-146)."""

import numpy as np
import pytest

import jax

from ggrs_tpu import SessionBuilder
from ggrs_tpu.errors import MismatchedChecksum
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.tpu import TpuRollbackBackend

PLAYERS = 2
ENTITIES = 128


def make_backend(beam_width=0, device_verify=True, max_prediction=8):
    return TpuRollbackBackend(
        ExGame(PLAYERS, ENTITIES),
        max_prediction=max_prediction,
        num_players=PLAYERS,
        beam_width=beam_width,
        device_verify=device_verify,
    )


def make_session(check_distance=4, max_prediction=8):
    return (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(max_prediction)
        .with_check_distance(check_distance)
        .with_device_checksum_verification()
        .start_synctest_session()
    )


def drive(backend, frames, sess=None, check_distance=4, inputs_for=None,
          start=0):
    sess = sess or make_session(check_distance)
    inputs_for = inputs_for or (lambda t, h: bytes([(t * (3 + h) + h) % 16]))
    for t in range(start, start + frames):
        for h in range(PLAYERS):
            sess.add_local_input(h, inputs_for(t, h))
        backend.handle_requests(sess.advance_frame())
    return sess


def test_clean_run_verdict_clean():
    backend = make_backend()
    drive(backend, 60)
    backend.check()  # no divergence: must not raise
    mismatch, frame = backend.core.check_device_verdict()
    assert not mismatch and frame == -1


def test_injected_ring_corruption_is_latched():
    """Corrupt a saved snapshot between ticks: the next re-save of that
    frame recomputes a different checksum than first recorded — the device
    latch must trip with the right frame and stay tripped."""
    backend = make_backend()
    sess = drive(backend, 30, check_distance=4)
    backend.check()
    core = backend.core
    # corrupt the frame the NEXT tick's rollback loads (current - d): any
    # later frame's slot is re-saved clean before it would be read
    bad_frame = backend.current_frame - 4
    slot = bad_frame % core.ring_len
    core.ring = {
        **core.ring,
        "pos": core.ring["pos"].at[slot, 0, 0].add(7),
    }
    drive(backend, 10, sess=sess, start=30)
    # the first divergent RE-SAVE is the frame after the corrupted load
    # (the loaded frame itself is not re-saved by the request grammar)
    with pytest.raises(MismatchedChecksum) as exc:
        backend.check()
    assert exc.value.frame == bad_frame + 1
    # the latch holds the FIRST mismatching frame even as the run continues
    drive(backend, 10, sess=sess, start=40)
    with pytest.raises(MismatchedChecksum) as exc2:
        backend.check()
    assert exc2.value.frame == bad_frame + 1


def test_device_verify_through_beam_adoption():
    """Adopted rollbacks feed the same device history (their checksums come
    from the speculation): constant inputs make every rollback adopt, and
    the verdict must stay clean — then an injected corruption must still
    be caught on the resim that re-saves it."""
    backend = make_backend(beam_width=8)
    drive(backend, 40, check_distance=3, inputs_for=lambda t, h: bytes([h + 1]))
    assert backend.beam_hits > 10
    backend.check()


def test_requires_device_verify_flag():
    backend = make_backend(device_verify=False)
    drive(backend, 10)
    with pytest.raises(AssertionError):
        backend.check()


def test_no_readbacks_during_run(monkeypatch):
    """The whole point: a device-verified run transfers nothing back per
    tick. Count device_get calls AND ledger flushes (the two device->host
    paths) across 40 ticks — only the final check() may fetch, once."""
    backend = make_backend()
    sess = drive(backend, 5)  # warm/compile outside the counted window
    gets, flushes = [], []
    orig = jax.device_get
    monkeypatch.setattr(jax, "device_get", lambda x: (gets.append(1), orig(x))[1])
    monkeypatch.setattr(backend.ledger, "flush", lambda: flushes.append(1))
    drive(backend, 40, sess=sess, start=5)
    assert sum(gets) == 0 and sum(flushes) == 0, "run performed readbacks"
    # nobody resolved any checksum batch either
    assert all(b._np is None for b in backend.ledger._pending)
    backend.check()
    assert sum(gets) == 1


def test_mispaired_flush_fails_loudly():
    """A device-verify session must not silently no-op host verification
    APIs (a mispaired run would report vacuous success)."""
    from ggrs_tpu.errors import InvalidRequest

    sess = make_session()
    with pytest.raises(InvalidRequest, match="backend.check"):
        sess.flush_checksum_checks()
