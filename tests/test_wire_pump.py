"""Batched wire pump parity (network/pump.py).

The batched pump replaces the per-message decode/apply/send loops with
pooled one-pass decodes and field-level appliers; these tests pin that
the replacement is BIT-IDENTICAL to the legacy path it displaced:

  1. decode parity: batch_decode over randomized valid / truncated /
     oversized / garbage datagram streams reconstructs exactly the
     messages decode_all accepts — and drops exactly what it drops;
  2. endpoint-state parity: the same hostile stream applied through
     handle_decoded vs handle_message leaves two identically-seeded
     PeerEndpoints in identical observable state;
  3. session parity: a lossy 2x2 P2P mesh driven batched vs legacy
     produces identical checksum histories and connect status (native
     endpoints ride along where the library is built);
  4. hosted parity: an 8-session SessionHost fleet run batched vs with
     the pre-batched per-session pump pins bitwise checksum/ring/state
     equality on every device slot.
"""

import random

import numpy as np
import pytest

from ggrs_tpu import DesyncDetection, PlayerType, SessionBuilder, SessionState
from ggrs_tpu.native import available
from ggrs_tpu.network.messages import (
    InputMsg,
    ChecksumReport,
    InputAck,
    KeepAlive,
    Message,
    QualityReply,
    QualityReport,
    SyncReply,
    SyncRequest,
    decode_all,
    encode_message,
)
from ggrs_tpu.network.protocol import PeerEndpoint
from ggrs_tpu.network.pump import batch_decode, decode_record, record_to_message
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.sync_layer import ConnectionStatus, PendingChecksumReport
from ggrs_tpu.utils.clock import FakeClock


def random_body(rng):
    kind = rng.randrange(8)
    if kind == 0:
        return SyncRequest(rng.getrandbits(32))
    if kind == 1:
        return SyncReply(rng.getrandbits(32))
    if kind == 2:
        n_status = rng.randrange(0, 5)
        return InputMsg(
            peer_connect_status=[
                ConnectionStatus(bool(rng.randrange(2)),
                                 rng.randrange(-1, 1000))
                for _ in range(n_status)
            ],
            disconnect_requested=bool(rng.randrange(2)),
            start_frame=rng.randrange(-1, 1000),
            ack_frame=rng.randrange(-1, 1000),
            bytes_=bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 40))),
        )
    if kind == 3:
        return InputAck(rng.randrange(-1, 1000))
    if kind == 4:
        return QualityReport(rng.randrange(-128, 128), rng.getrandbits(48))
    if kind == 5:
        return QualityReply(rng.getrandbits(48))
    if kind == 6:
        return ChecksumReport(checksum=rng.getrandbits(128),
                              frame=rng.randrange(0, 1000))
    return KeepAlive()


def random_stream(rng, n):
    """(addr, wire) pairs: valid, truncated, oversized-trailer, garbage."""
    out = []
    for i in range(n):
        addr = f"peer{rng.randrange(3)}"
        roll = rng.random()
        wire = encode_message(
            Message(rng.randrange(1, 1 << 16), random_body(rng))
        )
        if roll < 0.55:
            pass  # valid as encoded
        elif roll < 0.7:
            wire = wire[: rng.randrange(0, len(wire))]  # truncated
        elif roll < 0.85:
            # oversized: trailing garbage the codec must ignore
            wire = wire + bytes(rng.randrange(256)
                                for _ in range(rng.randrange(1, 20)))
        else:
            wire = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 64)))
        out.append((addr, wire))
    return out


def test_batch_decode_matches_legacy_decode_all():
    """Record-for-record decode parity over randomized hostile streams."""
    for seed in range(20):
        rng = random.Random(seed)
        pairs = random_stream(rng, 120)
        legacy = dict()
        for i, (addr, wire) in enumerate(pairs):
            got = decode_all([(addr, wire)])
            legacy[i] = got[0][1] if got else None
        records = batch_decode(
            [(0, addr, wire) for addr, wire in pairs]
        )
        assert len(records) == len(pairs)
        # the scalar small-pass twin must agree record-for-record with
        # the vectorized path (statuses normalize to tuples of pairs)
        for (_, wire), rec in zip(pairs, records):
            scalar = decode_record(wire)
            if rec is None:
                assert scalar is None
            else:
                norm = rec[:5] + (
                    tuple(tuple(s) for s in rec[5]), rec[6]
                )
                snorm = scalar[:5] + (
                    tuple(tuple(s) for s in scalar[5]), scalar[6]
                )
                assert norm == snorm
        for i, ((addr, wire), rec) in enumerate(zip(pairs, records)):
            if legacy[i] is None:
                assert rec is None, (
                    f"seed {seed} datagram {i}: batched decoded what "
                    f"legacy dropped ({wire!r})"
                )
                continue
            assert rec is not None, (
                f"seed {seed} datagram {i}: batched dropped what legacy "
                f"decoded ({legacy[i]})"
            )
            msg = record_to_message(rec, wire)
            assert msg.magic == legacy[i].magic
            assert msg.body == legacy[i].body, (
                f"seed {seed} datagram {i}: {msg.body} != {legacy[i].body}"
            )
            # wire stamp: recv byte accounting must see the datagram size
            assert msg._wire == legacy[i]._wire


def make_endpoint(seed, clock):
    return PeerEndpoint(
        handles=[1], peer_addr="peer", num_players=2, local_players=1,
        max_prediction=8, disconnect_timeout_ms=2000,
        disconnect_notify_start_ms=500, fps=60, input_size=1,
        clock=clock, rng=random.Random(seed),
    )


def endpoint_state(ep):
    return {
        "state": ep.state,
        "remote_magic": ep.remote_magic,
        "packets_recv": ep.packets_recv,
        "bytes_recv": ep.bytes_recv,
        "packets_sent": ep.packets_sent,
        "bytes_sent": ep.bytes_sent,
        "pending": list(ep.pending_output),
        "last_acked": ep.last_acked_input,
        "recv_inputs": dict(ep.recv_inputs),
        "connect": [(s.disconnected, s.last_frame)
                    for s in ep.peer_connect_status],
        "checksums": dict(ep.checksum_history),
        "rtt": ep.round_trip_time,
        "remote_adv": ep.remote_frame_advantage,
        "events": list(ep.event_queue),
        "sends": [encode_message(m) for m in ep.send_queue],
    }


def test_endpoint_handle_decoded_matches_handle_message():
    """The same stream through the field-level applier vs the object
    applier must leave identically-seeded endpoints bit-identical."""
    for seed in range(8):
        rng = random.Random(1000 + seed)
        clock = FakeClock()
        a = make_endpoint(seed, clock)
        b = make_endpoint(seed, clock)
        a.synchronize()
        b.synchronize()
        pairs = random_stream(rng, 150)
        records = batch_decode([(0, addr, w) for addr, w in pairs])
        for (addr, wire), rec in zip(pairs, records):
            if rec is None:
                continue
            msg = record_to_message(rec, wire)
            a.handle_message(msg)
            b.handle_decoded(
                rec[0], rec[1], len(wire),
                rec[2], rec[3], rec[4], rec[5], rec[6],
            )
            clock.advance(7)
        assert endpoint_state(a) == endpoint_state(b), f"seed {seed}"


def drive_mesh(batched, use_native, ticks=120, loss=0.05, seed=5):
    """A 2-player P2P mesh over a seeded lossy wire; returns per-session
    observable outcomes. All nondeterminism is seeded, so batched and
    legacy runs see byte-identical traffic unless behavior diverges."""
    from stubs import GameStub

    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=15, jitter_ms=5, loss=loss,
                          seed=seed)

    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(8)
            .with_clock(clock)
            .with_desync_detection_mode(DesyncDetection.on(interval=10))
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        if use_native:
            b = b.with_native_endpoints(True)
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        return b.start_p2p_session(net.socket(my_addr))

    sessions = [build("a", "b", 0), build("b", "a", 1)]
    games = [GameStub(), GameStub()]
    for s in sessions:
        s.batched_pump = batched
    for _ in range(400):
        for s in sessions:
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(s.current_state() == SessionState.RUNNING for s in sessions):
            break
    else:
        raise AssertionError("mesh failed to synchronize")

    script = random.Random(seed ^ 0xBEEF)
    inputs = [[script.randrange(16) for _ in range(ticks)] for _ in range(2)]
    for t in range(ticks):
        for i, s in enumerate(sessions):
            s.add_local_input(i, bytes([inputs[i][t]]))
            games[i].handle_requests(s.advance_frame())
            s.events()
        clock.advance(16)
    return [
        {
            "frame": s.current_frame,
            "checksum_history": dict(s.local_checksum_history),
            "connect": [(c.disconnected, c.last_frame)
                        for c in s.local_connect_status],
            "game_state": (g.gs.frame, g.gs.state),
        }
        for s, g in zip(sessions, games)
    ]


@pytest.mark.parametrize("use_native", [False] + ([True] if available() else []))
def test_session_parity_batched_vs_legacy(use_native):
    """Lossy mesh: batched pump vs legacy per-message pump, identical
    outcomes (checksum history is the bitwise witness)."""
    batched = drive_mesh(True, use_native)
    legacy = drive_mesh(False, use_native)
    assert batched == legacy
    # the run must actually exercise desync detection's checksum lane
    assert batched[0]["checksum_history"]


def test_pending_checksum_report_serial_guard():
    """Non-forced flushes must not bind entries captured within the
    serial guard — their correcting rollback may be unfulfilled."""

    class Cell:
        def __init__(self, frame):
            self.frame = frame
            self.bound = 0

        def checksum_getter(self):
            self.bound += 1
            return lambda: 123

    pcr = PendingChecksumReport()
    young = Cell(20)
    old = Cell(10)
    pcr.capture(10, old, serial=5)
    pcr.capture(20, young, serial=9)
    emitted = []
    pcr.flush(force=False, emit=lambda f, c: emitted.append(f), max_serial=7)
    assert emitted == [10]
    assert old.bound == 1 and young.bound == 0
    # the forced flush (max_serial=None) drains everything, as before
    pcr.flush(force=True, emit=lambda f, c: emitted.append(f))
    assert emitted == [10, 20]
    assert young.bound == 1


def build_hosted_fleet(batched, seed=13):
    from ggrs_tpu.models.ex_game import ExGame
    from ggrs_tpu.serve import SessionHost
    from ggrs_tpu.serve.loadgen import (
        build_matches,
        drive_scripted,
        make_scripts,
        sync_fleet,
    )

    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=20, jitter_ms=8, loss=0.03,
                          seed=seed)
    host = SessionHost(
        ExGame(num_players=4, num_entities=16),
        max_prediction=8, num_players=4, max_sessions=12,
        clock=clock, idle_timeout_ms=0, batched_pump=batched,
    )
    matches = build_matches(host, net, clock, sessions=8, seed=seed)
    sync_fleet(host, matches, clock)
    ticks = 60
    scripts = make_scripts(matches, ticks, seed=seed)
    desyncs = drive_scripted(host, matches, clock, scripts, ticks)
    assert not desyncs, f"hosted fleet desynced (batched={batched})"
    host.device.block_until_ready()
    return host, matches


def test_hosted_fleet_parity_batched_vs_prebatched_pump():
    """8-session hosted run, batched fleet pump vs the pre-batched
    per-session pump: bitwise state/ring parity on every device slot,
    identical checksum histories on every session."""
    host_a, matches_a = build_hosted_fleet(True)
    host_b, matches_b = build_hosted_fleet(False)
    assert host_a.batched_pump and not host_b.batched_pump
    keys_a = [k for keys in matches_a for k in keys]
    keys_b = [k for keys in matches_b for k in keys]
    assert len(keys_a) == len(keys_b) >= 8
    for ka, kb in zip(keys_a, keys_b):
        sa, sb = host_a.session(ka), host_b.session(kb)
        assert sa.current_frame == sb.current_frame
        assert sa.local_checksum_history == sb.local_checksum_history
        slot_a = host_a._lanes[ka].slot
        slot_b = host_b._lanes[kb].slot
        state_a = host_a.device.state_numpy(slot_a)
        state_b = host_b.device.state_numpy(slot_b)
        leaves_a, _ = _tree_flatten(state_a)
        leaves_b, _ = _tree_flatten(state_b)
        for la, lb in zip(leaves_a, leaves_b):
            assert np.array_equal(np.asarray(la), np.asarray(lb))
    # ring parity across the whole stacked fleet
    import jax

    ra = jax.device_get(host_a.device.rings)
    rb = jax.device_get(host_b.device.rings)
    for la, lb in zip(jax.tree.leaves(ra), jax.tree.leaves(rb)):
        assert np.array_equal(np.asarray(la), np.asarray(lb))


def _tree_flatten(tree):
    import jax

    return jax.tree.flatten(tree)
