"""Test env: force jax onto a virtual 8-device CPU platform so multi-chip
sharding tests run without TPU hardware.

IMPORTANT: this image boots an `axon` TPU-tunnel PJRT plugin from
sitecustomize, which programmatically sets jax_platforms="axon,cpu" —
overriding any JAX_PLATFORMS env var. jax is therefore already imported by
the time conftest runs, and the only effective override is jax.config.
XLA_FLAGS is still read lazily at first CPU-client creation, so setting it
here (before any backend init) works.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
