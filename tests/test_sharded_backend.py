"""Entity-sharded product backend: a world partitioned over the mesh's
`entity` axis must run inside real sessions (SyncTest AND P2P) with
bit-parity vs the unsharded backend — state, checksums, and the desync
detector all agree. This is the multi-chip request path (the rollback seam
src/sessions/p2p_session.rs:621-673 executed over a device mesh,
BASELINE.json configs[4])."""

import random

import numpy as np
import pytest

from ggrs_tpu import (
    DesyncDetected,
    DesyncDetection,
    PlayerType,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.models import ex_game
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.parallel.mesh import make_mesh
from ggrs_tpu.tpu import TpuRollbackBackend
from ggrs_tpu.utils.clock import FakeClock

NUM_PLAYERS = 2
ENTITIES = 128  # divisible by the 4-wide entity axis of the 8-device mesh

# History: on jax versions without a top-level jax.shard_map (< 0.6),
# four sharded parity tests here were KNOWN-RED and skip-gated. The root
# cause was never the jax.experimental.shard_map compat shim in
# ggrs_tpu/parallel/sharded.py: jax 0.4.x GSPMD miscompiles
# `sum(concatenate([...]))` of an entity-sharded operand on a multi-axis
# mesh into an all-reduce over EVERY mesh axis, so a world replicated
# over the 2-wide `beam` axis reported exactly 2x the true checksum. The
# models' `_checksum_generic` now computes per-key partial sums with
# global word offsets (ops/fixed_point.weighted_checksum_parts —
# bit-identical totals, no concatenate), and all four tests pass under
# the shim on jax 0.4.37 as well as under the native jax.shard_map.
import jax  # noqa: F401  (kept: the fixture and parity tests poke jax)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)  # (beam=2, entity=4) on the virtual CPU devices


def make_backend(mesh=None, beam_width=0, max_prediction=8):
    game = ex_game.ExGame(NUM_PLAYERS, ENTITIES)
    return TpuRollbackBackend(
        game,
        max_prediction=max_prediction,
        num_players=NUM_PLAYERS,
        beam_width=beam_width,
        mesh=mesh,
    )


def drive_synctest(handler, frames, check_distance, max_prediction=8, seed=3):
    sess = (
        SessionBuilder(input_size=1)
        .with_num_players(NUM_PLAYERS)
        .with_max_prediction_window(max_prediction)
        .with_check_distance(check_distance)
        .start_synctest_session()
    )
    rng = np.random.default_rng(seed)
    for _ in range(frames):
        for h in range(NUM_PLAYERS):
            sess.add_local_input(h, bytes([int(rng.integers(0, 16))]))
        handler.handle_requests(sess.advance_frame())
    return sess


def assert_state_equal(a, b):
    for key in ("frame", "pos", "vel", "rot"):
        np.testing.assert_array_equal(np.asarray(a[key]), np.asarray(b[key]))


def test_sharded_state_placement(mesh):
    backend = make_backend(mesh)
    ent = mesh.shape["entity"]
    # entity arrays actually split: each device holds N/ent rows
    shard = backend.core.state["pos"].addressable_shards[0]
    assert shard.data.shape[0] == ENTITIES // ent
    ring_shard = backend.core.ring["pos"].addressable_shards[0]
    assert ring_shard.data.shape == (
        backend.core.ring_len + 1,
        ENTITIES // ent,
        2,
    )


@pytest.mark.parametrize("check_distance", [2, 7])
def test_sharded_backend_bit_parity(mesh, check_distance):
    """Same request stream through the sharded and unsharded backends:
    final state and every saved checksum must be bitwise identical."""
    sharded = make_backend(mesh)
    plain = make_backend(None)
    drive_synctest(sharded, 50, check_distance)
    drive_synctest(plain, 50, check_distance)
    assert_state_equal(sharded.state_numpy(), plain.state_numpy())


def test_sharded_backend_with_beam(mesh):
    """Beam speculation over the sharded core: candidate futures shard the
    `beam` axis, adoption still bit-matches the plain resim path."""
    def drive_constant(handler, frames):
        sess = (
            SessionBuilder(input_size=1)
            .with_num_players(NUM_PLAYERS)
            .with_max_prediction_window(8)
            .with_check_distance(3)
            .start_synctest_session()
        )
        for _ in range(frames):
            for h in range(NUM_PLAYERS):
                sess.add_local_input(h, bytes([h + 1]))
            handler.handle_requests(sess.advance_frame())

    sharded = make_backend(mesh, beam_width=8)
    plain = make_backend(None)
    drive_constant(sharded, 40)
    drive_constant(plain, 40)
    assert_state_equal(sharded.state_numpy(), plain.state_numpy())
    # a constant script makes the repeat-last member the corrected script:
    # the sharded adopt path must actually run
    assert sharded.beam_hits > 0


def test_sharded_backend_with_lazy_ticks(mesh):
    """Lazy tick batching composes with the mesh-sharded core: the fused
    multi-tick scan runs under GSPMD over the entity axis, bit-matching
    the plain per-tick sharded backend (and the unsharded one)."""
    sharded_plain = make_backend(mesh)
    sharded_lazy = TpuRollbackBackend(
        ex_game.ExGame(NUM_PLAYERS, ENTITIES),
        max_prediction=8,
        num_players=NUM_PLAYERS,
        mesh=mesh,
        lazy_ticks=5,
    )
    drive_synctest(sharded_lazy, 30, check_distance=3)
    drive_synctest(sharded_plain, 30, check_distance=3)
    assert_state_equal(sharded_lazy.state_numpy(), sharded_plain.state_numpy())
    unsharded = make_backend(None)
    drive_synctest(unsharded, 30, check_distance=3)
    assert_state_equal(sharded_lazy.state_numpy(), unsharded.state_numpy())


def test_sharded_pallas_tick_bit_parity(mesh):
    """The sharded request path on the entity-tiled pallas kernel
    (ShardedPallasTickCore: one local kernel per device + psum'd checksum
    partials) must bit-match the sharded XLA scan AND the unsharded
    backend — state, ring, and every saved checksum. Lazy ticks force the
    multi-row dispatches the kernel serves; the forced-rollback SyncTest
    stream exercises loads, masked saves, and resim inside the kernel."""
    # 512 entities: each of the 4 entity shards gets one 128-lane tile
    game = ex_game.ExGame(NUM_PLAYERS, 512)
    sharded_pallas = TpuRollbackBackend(
        game,
        max_prediction=8,
        num_players=NUM_PLAYERS,
        mesh=mesh,
        lazy_ticks=5,
        tick_backend="pallas-interpret",
    )
    assert sharded_pallas.core.tick_backend == "pallas-interpret"
    sharded_xla = TpuRollbackBackend(
        ex_game.ExGame(NUM_PLAYERS, 512),
        max_prediction=8,
        num_players=NUM_PLAYERS,
        mesh=mesh,
        lazy_ticks=5,
        tick_backend="xla",
    )
    drive_synctest(sharded_pallas, 30, check_distance=3)
    drive_synctest(sharded_xla, 30, check_distance=3)
    assert_state_equal(sharded_pallas.state_numpy(), sharded_xla.state_numpy())
    unsharded = TpuRollbackBackend(
        ex_game.ExGame(NUM_PLAYERS, 512),
        max_prediction=8,
        num_players=NUM_PLAYERS,
    )
    drive_synctest(unsharded, 30, check_distance=3)
    assert_state_equal(sharded_pallas.state_numpy(), unsharded.state_numpy())
    # the sharded state is actually partitioned over the mesh
    shard = sharded_pallas.core.state["pos"].addressable_shards[0]
    assert shard.data.shape[0] == 512 // mesh.shape["entity"]


def test_sharded_pallas_beam_bit_parity(mesh):
    """The SHARDED pallas beam rollout (ShardedPallasBeamRollout: one
    local entity-tiled rollout per device, psum'd checksum partials —
    the restriction VERDICT r4 flagged at resim.py:204-207, lifted): a
    mesh-sharded backend speculating through the pallas kernel must
    adopt trajectories bit-identical to the sharded XLA speculation AND
    the unsharded backend."""
    from ggrs_tpu.tpu.pallas_beam import ShardedPallasBeamRollout

    def drive_constant(handler, frames):
        sess = (
            SessionBuilder(input_size=1)
            .with_num_players(NUM_PLAYERS)
            .with_max_prediction_window(8)
            .with_check_distance(3)
            .start_synctest_session()
        )
        for _ in range(frames):
            for h in range(NUM_PLAYERS):
                sess.add_local_input(h, bytes([h + 1]))
            handler.handle_requests(sess.advance_frame())

    def build(mesh_, spec_backend):
        return TpuRollbackBackend(
            ex_game.ExGame(NUM_PLAYERS, 512),
            max_prediction=8,
            num_players=NUM_PLAYERS,
            beam_width=8,
            mesh=mesh_,
            spec_backend=spec_backend,
        )

    sharded_pallas = build(mesh, "pallas-interpret")
    drive_constant(sharded_pallas, 40)
    # the sharded rollout actually ran (no silent XLA demotion) and the
    # constant script made the repeat-last member adopt
    assert sharded_pallas.core.spec_backend == "pallas-interpret"
    assert any(
        isinstance(r, ShardedPallasBeamRollout)
        for r in sharded_pallas.core._beam_rollouts.values()
    ), "mesh-sharded speculation did not use ShardedPallasBeamRollout"
    assert sharded_pallas.beam_hits > 0

    sharded_xla = build(mesh, "xla")
    drive_constant(sharded_xla, 40)
    assert_state_equal(
        sharded_pallas.state_numpy(), sharded_xla.state_numpy()
    )
    unsharded = TpuRollbackBackend(
        ex_game.ExGame(NUM_PLAYERS, 512),
        max_prediction=8,
        num_players=NUM_PLAYERS,
        beam_width=8,
    )
    drive_constant(unsharded, 40)
    assert_state_equal(sharded_pallas.state_numpy(), unsharded.state_numpy())


def test_sharded_pallas_tick_checksums_and_verify(mesh):
    """Checksum values read back through the lazy ledger and the on-device
    verify verdict must agree between the sharded pallas tick kernel and
    the unsharded XLA path (psum'd partial sums == unsharded totals,
    bit-for-bit)."""
    from ggrs_tpu.tpu.resim import ResimCore

    rng = np.random.default_rng(11)
    game_a = ex_game.ExGame(NUM_PLAYERS, 512)
    game_b = ex_game.ExGame(NUM_PLAYERS, 512)
    sharded = ResimCore(
        game_a, 8, NUM_PLAYERS, mesh=mesh, device_verify=True,
        tick_backend="pallas-interpret",
    )
    plain = ResimCore(game_b, 8, NUM_PLAYERS, device_verify=True)
    W, P = sharded.window, NUM_PLAYERS
    # a hand-driven multi-row buffer: row 0 plain advance+saves, row 1 a
    # rollback (load + resim), row 2 padding
    rows = []
    frame = 0
    for t in range(2):
        inputs = rng.integers(0, 16, size=(W, P, 1), dtype=np.uint8)
        statuses = np.zeros((W, P), dtype=np.int32)
        save_slots = np.full((W,), sharded.scratch_slot, dtype=np.int32)
        count = 3
        for i in range(count + 1):
            save_slots[i] = (frame + i) % sharded.ring_len
        rows.append(
            sharded.pack_tick_row(
                t == 1, frame % sharded.ring_len, inputs, statuses,
                save_slots, count, start_frame=frame,
            )
        )
        if t == 0:
            frame += count
            frame -= count  # rollback row reloads the same base
    rows.append(sharded.pad_tick_row())
    buf = np.stack(rows)
    his_s, los_s = sharded.tick_multi(buf)
    his_p, los_p = plain.tick_multi(buf.copy())
    np.testing.assert_array_equal(np.asarray(his_s), np.asarray(his_p))
    np.testing.assert_array_equal(np.asarray(los_s), np.asarray(los_p))
    assert sharded.check_device_verdict() == plain.check_device_verdict()
    for key in ("pos", "vel", "rot", "frame"):
        np.testing.assert_array_equal(
            np.asarray(sharded.state[key]), np.asarray(plain.state[key])
        )
        np.testing.assert_array_equal(
            np.asarray(sharded.ring[key]), np.asarray(plain.ring[key])
        )


def test_sharded_checkpoint_roundtrip(tmp_path, mesh):
    backend = make_backend(mesh)
    drive_synctest(backend, 20, check_distance=2)
    path = str(tmp_path / "ckpt.npz")
    backend.save(path)

    game = ex_game.ExGame(NUM_PLAYERS, ENTITIES)
    # restore sharded -> unsharded and vice versa: layout-agnostic
    plain = TpuRollbackBackend.restore(path, game)
    resharded = TpuRollbackBackend.restore(path, game, mesh=mesh)
    assert_state_equal(plain.state_numpy(), backend.state_numpy())
    assert_state_equal(resharded.state_numpy(), backend.state_numpy())
    shard = resharded.core.state["pos"].addressable_shards[0]
    assert shard.data.shape[0] == ENTITIES // mesh.shape["entity"]


# ---------------------------------------------------------------------------
# the decisive end-to-end: a sharded world inside a live P2P session
# ---------------------------------------------------------------------------


def build_pair(clock, net):
    def build(my_addr, other_addr, local_handle):
        return (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(8)
            .with_desync_detection_mode(DesyncDetection.on(interval=10))
            .with_clock(clock)
            # seed from the handle, NOT hash(addr): string hashing is
            # per-process randomized, which would make handshake timing
            # (and any marginal failure) unreproducible across runs
            .with_rng(random.Random(1234 + local_handle))
            .add_player(PlayerType.local(), local_handle)
            .add_player(PlayerType.remote(other_addr), 1 - local_handle)
            .start_p2p_session(net.socket(my_addr))
        )

    return build("a", "b", 0), build("b", "a", 1)


def sync_sessions(sessions, clock):
    for _ in range(400):
        for s in sessions:
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(s.current_state() == SessionState.RUNNING for s in sessions):
            return
    raise AssertionError("sessions failed to synchronize")


def test_p2p_sharded_vs_unsharded_peer(mesh):
    """One peer runs the mesh-sharded backend, the other the single-device
    backend, desync detection on: the framework's own detector must stay
    silent for the whole run (checksums bit-agree across layouts), and the
    final worlds must match."""
    clock = FakeClock()
    net = InMemoryNetwork(clock=clock)
    sess_a, sess_b = build_pair(clock, net)
    back_a = make_backend(mesh)
    back_b = make_backend(None)
    sync_sessions([sess_a, sess_b], clock)

    rng = np.random.default_rng(7)
    desyncs = []
    for frame in range(60):
        for sess, backend, handle in ((sess_a, back_a, 0), (sess_b, back_b, 1)):
            sess.poll_remote_clients()
            desyncs += [e for e in sess.events() if isinstance(e, DesyncDetected)]
            sess.add_local_input(handle, bytes([int(rng.integers(0, 16))]))
            backend.handle_requests(sess.advance_frame())
        clock.advance(17)
    # let in-flight inputs land, then advance twice more so each peer's
    # pending rollbacks run and its ring slots at confirmed frames are final
    for _ in range(10):
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        clock.advance(17)
    for _ in range(2):
        for sess, backend, handle in ((sess_a, back_a, 0), (sess_b, back_b, 1)):
            sess.poll_remote_clients()
            desyncs += [e for e in sess.events() if isinstance(e, DesyncDetected)]
            sess.add_local_input(handle, b"\x00")
            backend.handle_requests(sess.advance_frame())
        clock.advance(17)

    assert desyncs == [], f"sharded vs unsharded checksum mismatch: {desyncs[:3]}"
    assert back_a.current_frame == back_b.current_frame == 62
    assert sess_a.local_checksum_history and sess_b.local_checksum_history

    # bitwise cross-layout check: both rings hold the identical snapshot of
    # the last mutually-confirmed frame
    c = min(sess_a.confirmed_frame(), sess_b.confirmed_frame())
    assert c > 62 - back_a.core.ring_len, "confirmed frame fell out of the ring"
    snap_a = back_a.core.fetch_ring_slot(c % back_a.core.ring_len)
    snap_b = back_b.core.fetch_ring_slot(c % back_b.core.ring_len)
    assert int(np.asarray(snap_a["frame"])) == c
    assert_state_equal(snap_a, snap_b)
