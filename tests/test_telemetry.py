"""Session telemetry subsystem: metrics registry, flight recorder, exporters
and desync forensics (ggrs_tpu/obs)."""

import json
import os
import random
import re

import pytest

from ggrs_tpu import (
    DesyncDetection,
    PlayerType,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.obs import (
    GLOBAL_TELEMETRY,
    FlightRecorder,
    MetricsRegistry,
    Telemetry,
)
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.utils.clock import FakeClock
from stubs import GameStub, RandomChecksumGameStub


@pytest.fixture
def telemetry(tmp_path):
    """Enable the process-global telemetry for one test, clean slate, and
    guarantee it is disabled and zeroed again afterwards."""
    tel = GLOBAL_TELEMETRY
    tel.reset()
    tel.enabled = True
    tel.dump_dir = str(tmp_path)
    try:
        yield tel
    finally:
        tel.enabled = False
        tel.dump_dir = None
        tel.reset()


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "a counter", ("peer",))
    c.labels("a").inc()
    c.labels("a").inc(2)
    c.labels("b").inc()
    assert c.labels("a").value == 3
    assert c.labels("b").value == 1

    g = reg.gauge("g", "a gauge")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value == 4

    h = reg.histogram("h", "log2 buckets")
    for v in (0.5, 1, 3, 1000, 10**6):
        h.observe(v)
    snap = h.snapshot()["values"][""]
    assert snap["count"] == 5
    assert snap["buckets"]["1"] == 2  # 0.5 and 1.0 both land in le=1
    assert snap["buckets"]["4"] == 1
    assert snap["buckets"]["+Inf"] == 1  # 10**6 overflows the fixed buckets


def test_registry_kind_clash_raises():
    reg = MetricsRegistry()
    reg.counter("x", "")
    with pytest.raises(ValueError):
        reg.gauge("x", "")


def test_reset_keeps_bound_children_valid():
    reg = MetricsRegistry()
    bound = reg.counter("c_total", "", ("peer",)).labels("a")
    bound.inc(7)
    reg.reset()
    assert bound.value == 0
    bound.inc()  # the pre-bound child must still feed the registry
    assert reg.counter("c_total", "", ("peer",)).labels("a").value == 1


def test_flight_recorder_is_bounded_ring():
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("tick", frame=i)
    assert len(rec) == 4
    assert rec.total_recorded == 10
    frames = [e.frame for e in rec.tail()]
    assert frames == [6, 7, 8, 9]  # oldest dropped, order preserved
    assert rec.to_json(2)[-1]["frame"] == 9


def test_prometheus_text_format_is_parseable():
    reg = MetricsRegistry()
    reg.counter("a_total", "with \"quotes\"", ("peer",)).labels('x"y').inc()
    reg.gauge("b", "").set(1.5)
    reg.histogram("h_ms", "").observe(3)
    sample = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\})? -?[0-9.eE+-]+$'
    )
    for line in reg.prometheus_lines():
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*( .*)?$", line)
        else:
            assert sample.match(line), f"unparseable sample line: {line!r}"


def test_disabled_telemetry_records_nothing():
    tel = GLOBAL_TELEMETRY
    assert not tel.enabled  # process default
    before = tel.recorder.total_recorded
    session = (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_check_distance(2)
        .start_synctest_session()
    )
    game = GameStub()
    for frame in range(20):
        session.add_local_input(0, bytes([frame % 3]))
        session.add_local_input(1, bytes([frame % 5]))
        game.handle_requests(session.advance_frame())
    assert tel.recorder.total_recorded == before
    loads = tel.registry.get("ggrs_state_loads_total")
    assert loads is None or all(
        v == 0 for v in loads.snapshot()["values"].values()
    )


# ---------------------------------------------------------------------------
# session surfaces
# ---------------------------------------------------------------------------


def test_sync_test_session_telemetry(telemetry):
    session = (
        SessionBuilder(input_size=1)
        .with_num_players(2)
        .with_check_distance(2)
        .start_synctest_session()
    )
    game = GameStub()
    for frame in range(20):
        session.add_local_input(0, bytes([frame % 3]))
        session.add_local_input(1, bytes([frame % 5]))
        game.handle_requests(session.advance_frame())

    snap = session.telemetry()
    json.dumps(snap)  # JSON-serializable end to end
    assert snap["session"]["type"] == "sync_test"
    assert snap["session"]["current_frame"] == 20
    # forced rollbacks every deep-enough tick: metrics + flight events
    loads = snap["metrics"]["ggrs_state_loads_total"]["values"][""]
    assert loads > 0
    kinds = {e["kind"] for e in snap["events"]}
    assert {"rollback_begin", "rollback_end"} <= kinds
    depth = snap["metrics"]["ggrs_rollback_depth_frames"]["values"][""]
    assert depth["count"] == loads


def _p2p_pair(clock, net, desync=None):
    def build(my, other, handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(8)
            .with_clock(clock)
            .with_rng(random.Random(hash(my) & 0xFFFF))
        )
        if desync is not None:
            b = b.with_desync_detection_mode(desync)
        b = b.add_player(PlayerType.local(), handle)
        b = b.add_player(PlayerType.remote(other), 1 - handle)
        return b.start_p2p_session(net.socket(my))

    s1, s2 = build("a", "b", 0), build("b", "a", 1)
    for _ in range(400):
        for s in (s1, s2):
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(s.current_state() == SessionState.RUNNING for s in (s1, s2)):
            return s1, s2
    raise AssertionError("sessions failed to synchronize")


def test_p2p_session_telemetry_snapshot(telemetry):
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=40, seed=5)
    s1, s2 = _p2p_pair(clock, net)
    g1, g2 = GameStub(), GameStub()
    for frame in range(60):
        s1.add_local_input(0, bytes([frame % 7]))
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, bytes([(frame * 3) % 5]))
        g2.handle_requests(s2.advance_frame())
        s1.events()
        s2.events()
        clock.advance(16)

    snap = s1.telemetry()
    json.dumps(snap)
    sess = snap["session"]
    assert sess["type"] == "p2p" and sess["state"] == "running"
    assert sess["current_frame"] == 60
    # 40ms latency at 16ms frames: predictions must have missed -> accuracy < 1
    assert sess["prediction_accuracy"] and all(
        0.0 <= v < 1.0 for v in sess["prediction_accuracy"].values()
    )
    # per-peer network section carries the extended stats
    stats = sess["network"]["1"]
    assert stats["kbps_recv"] >= 0 and "jitter_ms" in stats and "packets_lost" in stats
    # wire counters moved in both directions
    m = snap["metrics"]
    assert m["ggrs_peer_bytes_sent_total"]["values"]["b"] > 0
    assert m["ggrs_peer_bytes_recv_total"]["values"]["b"] > 0
    # frame-advantage distribution recorded per peer
    assert m["ggrs_frame_advantage"]["values"]["b"]["count"] > 0
    # rollbacks happened under latency and were recorded
    assert m["ggrs_rollback_depth_frames"]["values"][""]["count"] > 0
    # prometheus export of the full live registry parses
    for line in GLOBAL_TELEMETRY.prometheus().strip().splitlines():
        assert line.startswith("#") or re.match(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? -?[0-9.eE+-]+$", line
        ), f"unparseable: {line!r}"


def test_spectator_session_telemetry(telemetry):
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    host = (
        SessionBuilder(input_size=1)
        .with_num_players(1)
        .with_clock(clock)
        .with_rng(random.Random(21))
        .add_player(PlayerType.local(), 0)
        .add_player(PlayerType.spectator("spec"), 1)
        .start_p2p_session(net.socket("host"))
    )
    spec = (
        SessionBuilder(input_size=1)
        .with_num_players(1)
        .with_clock(clock)
        .with_rng(random.Random(22))
        .start_spectator_session("host", net.socket("spec"))
    )
    for _ in range(60):
        host.poll_remote_clients()
        spec.poll_remote_clients()
        host.events()
        spec.events()
        clock.advance(20)
        if (
            host.current_state() == SessionState.RUNNING
            and spec.current_state() == SessionState.RUNNING
        ):
            break
    snap = spec.telemetry()
    json.dumps(snap)
    assert snap["session"]["type"] == "spectator"
    assert snap["session"]["state"] == "running"
    assert "network" in snap["session"]


def test_tracer_stats_fold_into_snapshot():
    from ggrs_tpu.utils.tracing import Tracer

    t = Tracer(enabled=True)
    with t.span("tick"):
        pass
    tel = Telemetry(enabled=True)
    snap = tel.snapshot(tracer=t)
    assert snap["tracer"]["tick"]["count"] == 1
    text = tel.prometheus(tracer=t)
    assert 'ggrs_tracer_span_count{span="tick"} 1' in text


# ---------------------------------------------------------------------------
# desync forensics
# ---------------------------------------------------------------------------


def test_forced_desync_emits_forensics_bundle(telemetry, tmp_path):
    clock = FakeClock()
    # latency forces mispredictions/rollbacks BEFORE the desync fires, so
    # the bundle's flight-recorder tail has rollback context to show
    net = InMemoryNetwork(clock, latency_ms=40, seed=17)
    s1, s2 = _p2p_pair(clock, net, desync=DesyncDetection.on(10))
    g1 = GameStub()
    g2 = RandomChecksumGameStub()  # checksums never agree -> guaranteed desync
    for frame in range(150):
        s1.add_local_input(0, bytes([frame % 7]))
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, bytes([(frame * 3) % 5]))
        g2.handle_requests(s2.advance_frame())
        s1.events()
        s2.events()
        clock.advance(16)

    dumps = sorted(os.listdir(tmp_path))
    assert dumps, "expected at least one desync forensics dump"
    bundle = json.load(open(os.path.join(tmp_path, dumps[0])))
    assert bundle["kind"] == "desync_forensics"
    assert bundle["frame"] >= 0
    assert bundle["local_checksum"] != bundle["remote_checksum"]
    assert isinstance(bundle["pending_predicted_inputs"], list)
    rollback_events = [
        e for e in bundle["events"] if e["kind"].startswith("rollback")
    ]
    assert rollback_events, "bundle must carry preceding rollback events"
    assert bundle["session"]["type"] == "p2p"
    # one dump per (peer, frame) per session: comparison intervals
    # re-detect the same divergence every pass but must not re-dump it.
    # Both sessions of the pair live in this process, so a frame may
    # appear at most twice (once per session), never more.
    frames_dumped = [
        json.load(open(os.path.join(tmp_path, d)))["frame"] for d in dumps
    ]
    assert all(frames_dumped.count(f) <= 2 for f in set(frames_dumped))


def test_forensics_dump_cap(telemetry, tmp_path):
    telemetry.MAX_FORENSICS_DUMPS  # class attr exists
    for i in range(Telemetry.MAX_FORENSICS_DUMPS + 5):
        telemetry.write_desync_forensics(
            frame=i, local_checksum=1, remote_checksum=2, addr="x"
        )
    assert len(os.listdir(tmp_path)) == Telemetry.MAX_FORENSICS_DUMPS


def test_session_events_have_typed_dict_forms():
    from ggrs_tpu.types import (
        DesyncDetected,
        Disconnected,
        Event,
        NetworkInterrupted,
        Synchronizing,
        WaitRecommendation,
    )
    from typing import get_args

    members = get_args(Event)
    assert Disconnected in members and DesyncDetected in members
    d = DesyncDetected(
        frame=7, local_checksum=1, remote_checksum=2, addr=("h", 9999)
    )
    out = d.to_dict()
    assert out["kind"] == "desync_detected" and out["frame"] == 7
    json.dumps(out)  # addr degraded to a JSON-able form
    assert Synchronizing(addr="a", total=5, count=1).to_dict()["kind"] == "synchronizing"
    assert NetworkInterrupted(addr="a", disconnect_timeout_ms=5).to_dict()[
        "disconnect_timeout_ms"
    ] == 5
    assert WaitRecommendation(skip_frames=3).to_dict()["skip_frames"] == 3
