"""Two real P2P sessions in one process over the virtual network —
multi-node-without-a-cluster, the reference's integration strategy
(tests/test_p2p_session.rs) plus latency/loss scenarios it never covered."""

import random

import pytest

from ggrs_tpu import (
    DesyncDetected,
    DesyncDetection,
    NotSynchronized,
    PlayerType,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.utils.clock import FakeClock
from stubs import GameStub, RandomChecksumGameStub


def build_pair(clock, net, *, desync=None, input_delay=0, max_prediction=8):
    def build(my_addr, other_addr, local_handle):
        b = (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(max_prediction)
            .with_input_delay(input_delay)
            .with_clock(clock)
            .with_rng(random.Random(hash(my_addr) & 0xFFFF))
        )
        if desync is not None:
            b = b.with_desync_detection_mode(desync)
        b = b.add_player(PlayerType.local(), local_handle)
        b = b.add_player(PlayerType.remote(other_addr), 1 - local_handle)
        return b.start_p2p_session(net.socket(my_addr))

    return build("a", "b", 0), build("b", "a", 1)


def sync_sessions(sessions, clock, iterations=400):
    for _ in range(iterations):
        for s in sessions:
            s.poll_remote_clients()
            s.events()
        clock.advance(20)
        if all(s.current_state() == SessionState.RUNNING for s in sessions):
            return
    raise AssertionError("sessions failed to synchronize")


def test_not_synchronized_before_handshake():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    s1, _s2 = build_pair(clock, net)
    s1.add_local_input(0, b"\x00")
    with pytest.raises(NotSynchronized):
        s1.advance_frame()


def test_lockstep_advance_zero_latency():
    """(tests/test_p2p_session.rs:99-146)"""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    s1, s2 = build_pair(clock, net)
    sync_sessions([s1, s2], clock)

    g1, g2 = GameStub(), GameStub()
    for frame in range(20):
        s1.add_local_input(0, bytes([frame % 5]))
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, bytes([(frame * 3) % 5]))
        g2.handle_requests(s2.advance_frame())
        clock.advance(16)

    assert s1.current_frame == 20 and s2.current_frame == 20
    assert g1.gs.frame == 20 and g2.gs.frame == 20


def finish_and_compare(s1, s2, g1, g2, clock, frames=60, latency_net=None):
    """Drive both sessions with scripted inputs; verify both replicas settle
    on identical confirmed state. Under heavy loss a session may legally
    stall on PredictionThreshold — skip the frame like a real client."""
    from ggrs_tpu import PredictionThreshold

    for frame in range(frames):
        for s, g, handle, mult, add in (
            (s1, g1, 0, 7, 1),
            (s2, g2, 1, 5, 2),
        ):
            try:
                s.add_local_input(handle, bytes([(frame * mult + add) % 16]))
                g.handle_requests(s.advance_frame())
            except PredictionThreshold:
                s.poll_remote_clients()  # window full: wait for the peer
        s1.events()
        s2.events()
        clock.advance(16)

    # drain the network so late inputs arrive, then advance one more frame on
    # each side so rollbacks apply the corrections
    for _ in range(10):
        s1.poll_remote_clients()
        s2.poll_remote_clients()
        clock.advance(16)
    s1.add_local_input(0, b"\x00")
    g1.handle_requests(s1.advance_frame())
    s2.add_local_input(1, b"\x00")
    g2.handle_requests(s2.advance_frame())

    # beyond the confirmed frame, states are still speculative; the corrected
    # (confirmed) prefix of the two replicas must be identical
    confirmed = min(s1.confirmed_frame(), s2.confirmed_frame())
    assert confirmed > frames // 2, "sessions never confirmed enough frames"
    for f in range(1, confirmed + 1):
        assert g1.history[f] == g2.history[f], f"replicas diverged at frame {f}"


def test_latency_forces_rollbacks_and_replicas_converge():
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=50, jitter_ms=20, seed=5)
    s1, s2 = build_pair(clock, net)
    sync_sessions([s1, s2], clock)
    g1, g2 = GameStub(), GameStub()
    finish_and_compare(s1, s2, g1, g2, clock)
    # with 50ms latency at 16ms frames, predictions MUST have missed sometimes
    assert g1.loaded_frames or g2.loaded_frames, "expected rollbacks under latency"


def test_loss_and_jitter_replicas_converge():
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=30, jitter_ms=30, loss=0.2, duplicate=0.1, seed=11)
    s1, s2 = build_pair(clock, net)
    sync_sessions([s1, s2], clock)
    g1, g2 = GameStub(), GameStub()
    finish_and_compare(s1, s2, g1, g2, clock)


def test_input_delay_replicas_converge():
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=40, seed=3)
    s1, s2 = build_pair(clock, net, input_delay=2)
    sync_sessions([s1, s2], clock)
    g1, g2 = GameStub(), GameStub()
    finish_and_compare(s1, s2, g1, g2, clock)


def test_no_desync_events_on_identical_games():
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=40, jitter_ms=10, seed=13)
    s1, s2 = build_pair(clock, net, desync=DesyncDetection.on(10))
    sync_sessions([s1, s2], clock)
    g1, g2 = GameStub(), GameStub()

    events = []
    for frame in range(120):
        s1.add_local_input(0, bytes([frame % 4]))
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, bytes([frame % 6]))
        g2.handle_requests(s2.advance_frame())
        events += s1.events() + s2.events()
        clock.advance(16)
    assert not [e for e in events if isinstance(e, DesyncDetected)]


def test_desync_detected_on_diverging_games():
    clock = FakeClock()
    net = InMemoryNetwork(clock, seed=17)
    s1, s2 = build_pair(clock, net, desync=DesyncDetection.on(10))
    sync_sessions([s1, s2], clock)
    g1 = GameStub()
    g2 = RandomChecksumGameStub()  # checksums will never agree

    events = []
    for frame in range(150):
        s1.add_local_input(0, b"\x01")
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, b"\x01")
        g2.handle_requests(s2.advance_frame())
        events += s1.events() + s2.events()
        clock.advance(16)
    assert [e for e in events if isinstance(e, DesyncDetected)]


def test_disconnect_player_and_continue():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    s1, s2 = build_pair(clock, net)
    sync_sessions([s1, s2], clock)
    g1 = GameStub()
    for frame in range(5):
        s1.add_local_input(0, b"\x01")
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, b"\x01")
        s2.advance_frame()
        clock.advance(16)

    s1.disconnect_player(1)
    # session keeps running; the dead player contributes dummy inputs
    for frame in range(10):
        s1.add_local_input(0, b"\x01")
        g1.handle_requests(s1.advance_frame())
        clock.advance(16)
    assert s1.current_frame == 15


def test_timeout_disconnect_via_silence():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    s1, s2 = build_pair(clock, net)
    sync_sessions([s1, s2], clock)
    g1 = GameStub()
    for frame in range(3):
        s1.add_local_input(0, b"\x01")
        g1.handle_requests(s1.advance_frame())
        s2.add_local_input(1, b"\x01")
        s2.advance_frame()
        clock.advance(16)

    # s2 goes silent; s1 sees interruption then disconnect after 2000ms
    from ggrs_tpu import Disconnected, NetworkInterrupted

    events = []
    for _ in range(30):
        s1.poll_remote_clients()
        events += s1.events()
        clock.advance(100)
    assert [e for e in events if isinstance(e, NetworkInterrupted)]
    assert [e for e in events if isinstance(e, Disconnected)]

    # and the session continues alone
    for frame in range(5):
        s1.add_local_input(0, b"\x01")
        g1.handle_requests(s1.advance_frame())
        clock.advance(16)
