"""Fleet control plane, in-process: wire framing, RPC retry/backoff and
circuit breaking, placement with FleetSaturated, wire-ticket fidelity,
the fencing contract, and rolling upgrades.

Everything here runs director + AgentCores in ONE process over real
kernel socketpairs with a shared FakeClock, so suspicion windows, retry
ladders and failovers are fully deterministic — the process-level soak
(tests/test_fleet_process.py, slow) re-runs the same machinery with
real SIGKILLs and wall clocks.
"""

import os

import pytest

from ggrs_tpu.errors import (
    CircuitOpen,
    FleetSaturated,
    RpcTimeout,
)
from ggrs_tpu.fleet.agent import AgentCore
from ggrs_tpu.fleet.chaos import compare_with_twin
from ggrs_tpu.fleet.director import Director
from ggrs_tpu.fleet.island import MatchSpec
from ggrs_tpu.fleet.rpc import CircuitBreaker, RetryPolicy, RpcPeer, call
from ggrs_tpu.fleet.wire import (
    FRAME_CALL,
    FRAME_REPLY,
    FrameError,
    conn_pair,
    decode_frames,
    encode_frame,
)
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.obs import GLOBAL_TELEMETRY
from ggrs_tpu.utils.clock import FakeClock

ENTITIES = 4


# ----------------------------------------------------------------------
# wire framing
# ----------------------------------------------------------------------

def test_wire_frame_roundtrip_and_partial_delivery():
    body = {"op": "spawn", "rid": 3, "nested": {"a": [1, 2]}}
    wire = encode_frame(FRAME_CALL, 7, body, b"\x00\x01blob")
    # whole frame plus a trailing partial: only the complete one parses,
    # the tail stays buffered
    buf = bytearray(wire + wire[:10])
    frames = decode_frames(buf)
    assert frames == [(FRAME_CALL, 7, body, b"\x00\x01blob")]
    assert bytes(buf) == wire[:10]
    # feeding the rest completes the second frame
    buf += wire[10:]
    assert decode_frames(buf) == [(FRAME_CALL, 7, body, b"\x00\x01blob")]
    assert not buf


def test_wire_frame_garbage_poisons_the_stream():
    buf = bytearray(b"\xff" * 32)
    with pytest.raises(FrameError):
        decode_frames(buf)


def test_conn_pair_partition_drops_both_ways():
    a, b = conn_pair()
    a.partitioned = True
    a.send(FRAME_CALL, 1, {"rid": 1, "op": "ping"})
    assert a.frames_dropped == 1
    a.partitioned = False
    b.send(FRAME_REPLY, 1, {"rid": 1, "ok": True})
    a.partitioned = True
    assert a.recv() == []  # arrived bytes are discarded, like a real cut
    a.partitioned = False
    assert a.recv() == []  # and they are GONE, not replayed after heal


# ----------------------------------------------------------------------
# rpc: retry schedule, breaker, duplicates
# ----------------------------------------------------------------------

def test_retry_policy_schedule_is_seeded_and_pinned():
    a = RetryPolicy(attempts=4, base_ms=50, max_ms=2000, seed=3)
    b = RetryPolicy(attempts=4, base_ms=50, max_ms=2000, seed=3)
    sched_a = [a.backoff_ms(i) for i in range(3)]
    sched_b = [b.backoff_ms(i) for i in range(3)]
    assert sched_a == sched_b  # deterministic per seed
    for i, d in enumerate(sched_a):
        base = 50 << i
        assert base // 2 <= d <= base  # jittered exponential envelope
    other = [RetryPolicy(seed=4).backoff_ms(i) for i in range(3)]
    assert other != sched_a  # different seed decorrelates


def test_circuit_breaker_open_halfopen_close():
    clock = FakeClock()
    br = CircuitBreaker(threshold=2, cooldown_ms=100)
    assert br.allow(clock.now_ms())
    br.record_failure(clock.now_ms())
    assert br.allow(clock.now_ms())  # one failure: still closed
    br.record_failure(clock.now_ms())
    assert not br.allow(clock.now_ms())  # threshold: open
    clock.advance(99)
    assert not br.allow(clock.now_ms())
    clock.advance(1)
    assert br.allow(clock.now_ms())  # half-open trial
    br.record_failure(clock.now_ms())
    assert not br.allow(clock.now_ms())  # trial failed: open again
    clock.advance(100)
    assert br.allow(clock.now_ms())
    br.record_success()
    assert br.state == CircuitBreaker.CLOSED


def test_rpc_timeout_then_circuit_open():
    clock = FakeClock()
    a, _b = conn_pair()  # nobody ever answers
    peer = RpcPeer(a, breaker=CircuitBreaker(threshold=1, cooldown_ms=500),
                   label="dead")
    policy = RetryPolicy(attempts=2, timeout_ms=50, base_ms=10, seed=0)
    retries0 = None
    tel = GLOBAL_TELEMETRY
    tel.enabled = True
    try:
        from ggrs_tpu.fleet.metrics import rpc_retries_total

        retries0 = rpc_retries_total().value
        with pytest.raises(RpcTimeout) as exc:
            call(peer, "ping", clock=clock, policy=policy,
                 on_wait=lambda: clock.advance(10))
        assert exc.value.attempts == 2
        assert rpc_retries_total().value == retries0 + 1  # 2nd attempt
        # breaker (threshold 1) is now open: the next call is refused
        # without touching the wire
        sent_before = a.frames_sent
        with pytest.raises(CircuitOpen):
            call(peer, "ping", clock=clock, policy=policy,
                 on_wait=lambda: clock.advance(10))
        assert a.frames_sent == sent_before
    finally:
        tel.enabled = False
        tel.reset()


def test_duplicate_calls_absorbed_by_reply_cache():
    a, b = conn_pair()
    caller, callee = RpcPeer(a), RpcPeer(b)
    executed = []

    def serve():
        for _ftype, epoch, body, _blob in b.recv():
            rid = body["rid"]
            if callee.replay_cached(rid):
                continue
            executed.append(rid)
            callee.reply(epoch, rid, {"pong": True})

    a.dup_next = 2  # the next call goes out three times
    clock = FakeClock()
    body, _ = call(caller, "ping", clock=clock,
                   policy=RetryPolicy(attempts=1, timeout_ms=1000, seed=0),
                   on_wait=lambda: (serve(), clock.advance(5)))
    assert body["pong"] is True
    serve()  # drain the duplicates still in the socket
    assert executed == [1]  # executed ONCE; dups hit the reply cache
    assert callee.reply_cache_hits == 2


# ----------------------------------------------------------------------
# the in-process rig
# ----------------------------------------------------------------------

class Rig:
    """Director + N AgentCores over socketpairs on one FakeClock."""

    def __init__(self, tmp_path, n_agents=2, *, max_sessions=8,
                 hb_interval_ms=50, suspicion_misses=4,
                 checkpoint_every=8, seed=1, **core_kw):
        self.clock = FakeClock()
        self.base = str(tmp_path)
        self.game = ExGame(num_players=2, num_entities=ENTITIES)
        self.director = Director(
            clock=self.clock, base_dir=self.base, seed=seed,
            hb_interval_ms=hb_interval_ms,
            suspicion_misses=suspicion_misses,
        )
        self.agents = []
        for i in range(n_agents):
            self.add_agent(max_sessions=max_sessions,
                           hb_interval_ms=hb_interval_ms,
                           checkpoint_every=checkpoint_every,
                           label=f"a{i}", **core_kw)
        self.director.on_wait = lambda: self.pump(1, 2)
        self.pump(10)
        assert len(self.director.hosts) == n_agents

    def add_agent(self, *, max_sessions=8, hb_interval_ms=50,
                  checkpoint_every=8, label="", **core_kw):
        a_conn, d_conn = conn_pair()
        core = AgentCore(
            self.game, base_dir=self.base, clock=self.clock,
            max_sessions=max_sessions, num_players=2,
            hb_interval_ms=hb_interval_ms,
            checkpoint_every=checkpoint_every, label=label, **core_kw,
        )
        core.attach_conn(a_conn)
        self.director.attach_conn(d_conn)
        core.start()
        self.agents.append(core)
        return core

    def pump(self, n=1, adv=10):
        for _ in range(n):
            for a in self.agents:
                a.step()
            self.director.step()
            self.director.heal_partitions()
            self.clock.advance(adv)

    def drive_done(self, cores=None, max_steps=4000):
        cores = cores if cores is not None else self.agents
        for _ in range(max_steps):
            self.pump(1)
            if all(
                i.done or i.failed
                for c in cores if c.terminated is None
                for i in c.islands.values()
            ):
                return
        raise AssertionError("islands failed to finish")


def _spec(mid, *, ticks=48, seed=0, wan=None):
    return MatchSpec(match_id=mid, players=2, ticks=ticks, seed=seed,
                     entities=ENTITIES, wan=wan)


# ----------------------------------------------------------------------
# placement / saturation
# ----------------------------------------------------------------------

def test_place_drive_and_twin_parity(tmp_path):
    rig = Rig(tmp_path)
    specs = [_spec(0, seed=100, wan={}), _spec(1, seed=101)]
    owners = {s.match_id: rig.director.place_match(s) for s in specs}
    assert sorted(owners.values()) == [0, 1]  # least-loaded spread
    rig.drive_done()
    reports = rig.director.collect_reports()
    for rep in reports.values():
        for entry in rep["islands"].values():
            assert entry["desyncs"] == 0
            assert entry["done"]
    parity = compare_with_twin(specs, reports, set())
    assert parity["clean_exact"], parity


def test_fleet_saturated_is_typed_with_occupancy(tmp_path):
    rig = Rig(tmp_path, max_sessions=2)
    rig.director.place_match(_spec(0))
    rig.director.place_match(_spec(1))
    t0 = rig.clock.now_ms()
    with pytest.raises(FleetSaturated) as exc:
        rig.director.place_match(_spec(2))
    assert exc.value.attempts >= rig.director.place_attempts
    assert exc.value.per_host == {"host0": "2/2", "host1": "2/2"}
    # the retry rounds actually backed off (jittered, clock advanced)
    assert rig.clock.now_ms() > t0


@pytest.mark.slow  # teardown mechanics; saturation/placement cover the
# admission accounting in tier-1
def test_release_match_frees_capacity(tmp_path):
    rig = Rig(tmp_path, max_sessions=2)
    rig.director.place_match(_spec(0, ticks=16))
    rig.director.place_match(_spec(1, ticks=16))
    rig.drive_done()
    rig.director.release_match(0)
    rig.director.release_match(1)
    rig.pump(3)
    rig.director.place_match(_spec(2, ticks=16))  # fits again


# ----------------------------------------------------------------------
# learned-model rollout: staged deploy + instant rollback
# ----------------------------------------------------------------------

def test_model_rollout_staged_with_instant_rollback(tmp_path):
    """The deploy plane (ggrs_tpu/learn/ -> fleet): rollout_model pushes
    a published blob to live hosts ONE at a time, heartbeats advertise
    the deployed version and live hit rate, and a hit-rate regression
    after a staged install instantly rolls every upgraded host back to
    the model it displaced (the agent-local undo buffer) and stops the
    rollout before the rest of the fleet is exposed."""
    import numpy as np

    from ggrs_tpu.learn import extract_examples, train_on_examples

    rig = Rig(tmp_path, speculation=True)
    # a tiny trained model matching the rig's game identity (2p, 1 byte)
    vals = []
    for c in range(10):
        vals += [5 if c % 2 == 0 else 9] * 6
    inputs = np.repeat(
        np.array(vals, dtype=np.uint8).reshape(-1, 1, 1), 2, axis=1
    )
    statuses = np.zeros(inputs.shape[:2], dtype=np.int32)
    model = train_on_examples(
        [extract_examples(inputs, statuses)], num_players=2, input_size=1,
    )

    model.version = 1
    res = rig.director.rollout_model(
        model.to_bytes(), version=1, drive=lambda: rig.pump(3),
    )
    assert res["installed"] == [0, 1] and not res["rolled_back"]
    assert res["skipped"] == {}
    for a in rig.agents:
        assert a.host.input_model_version == 1
    rig.pump(8)  # heartbeats advertise the deployed version + hit rate
    for hr in rig.director.hosts.values():
        assert hr.model_version == 1
        assert hr.model_hit_rate is not None

    # --- version 2 tanks host 0's hit rate: fleet-wide instant rollback
    spec0 = rig.agents[0].host._spec
    spec0.frames_draftable = 100
    spec0.frames_adopted = 60  # baseline 0.6 reported at the swap

    def regressing_drive():
        spec0.frames_adopted = 10  # post-deploy rate collapses to 0.1
        rig.pump(8)  # heartbeats carry the fresh rate to the director

    model.version = 2
    res2 = rig.director.rollout_model(
        model.to_bytes(), version=2, drive=regressing_drive,
    )
    assert res2["rolled_back"] and res2["regressed"] == 0
    assert res2["installed"] == [0]  # host 1 never saw version 2
    # every upgraded host is back on the displaced model, fleet-wide
    assert rig.agents[0].host.input_model_version == 1
    assert rig.agents[1].host.input_model_version == 1
    assert rig.director.hosts[0].model_version == 1


# ----------------------------------------------------------------------
# wire tickets: cross-host migration fidelity
# ----------------------------------------------------------------------

@pytest.mark.slow  # the fleet smoke + process soak pin this end to end;
# the in-tier-1 twin-parity witness is test_place_drive_and_twin_parity
def test_cross_process_migration_bitwise_vs_twin(tmp_path):
    rig = Rig(tmp_path)
    specs = [_spec(0, seed=7, wan={}, ticks=64), _spec(1, seed=8, ticks=64)]
    for s in specs:
        rig.director.place_match(s)
    # let the matches run, then live-migrate one mid-match over the wire
    for _ in range(30):
        rig.pump(1)
    src = rig.director.matches[0]["host"]
    dst = 1 - src
    rig.director.migrate_match(0, dst)
    assert rig.director.matches[0]["host"] == dst
    rig.drive_done()
    reports = rig.director.collect_reports()
    parity = compare_with_twin(specs, reports, set())
    # migration is observationally neutral: even the MIGRATED match is
    # bit-identical to the never-migrated twin
    assert parity["clean_exact"], parity


@pytest.mark.slow  # neutrality is also what the soak's faulted-match
# parity rests on; this isolates it when it ever breaks
def test_periodic_checkpoint_is_observationally_neutral(tmp_path):
    # same spec driven with aggressive checkpointing vs none: bitwise
    # identical outcomes (serialization must not perturb the run)
    rig = Rig(tmp_path, n_agents=1, checkpoint_every=4)
    spec = _spec(0, seed=42, wan={}, ticks=48)
    rig.director.place_match(spec)
    rig.drive_done()
    assert rig.agents[0].checkpoints_written > 3
    reports = rig.director.collect_reports()
    parity = compare_with_twin([spec], reports, set())
    assert parity["clean_exact"], parity


# ----------------------------------------------------------------------
# the fencing contract (stale epochs, zombie rejection, failover)
# ----------------------------------------------------------------------

def test_fencing_contract_end_to_end(tmp_path):
    tel = GLOBAL_TELEMETRY
    tel.enabled = True
    try:
        rig = Rig(tmp_path, checkpoint_every=6)
        specs = [_spec(0, seed=500, ticks=160), _spec(1, seed=501, ticks=160)]
        owners = {s.match_id: rig.director.place_match(s) for s in specs}
        for _ in range(40):
            rig.pump(1)
        victim = owners[0]
        vcore = rig.agents[victim]
        assert vcore.last_checkpoint is not None
        epoch_before = rig.director.hosts[victim].epoch

        # control partition long enough to trip suspicion: the agent
        # keeps ticking (the double-advance threat is real), the
        # director fences and fails over from the seized checkpoint
        vcore.partition(2_500)
        rig.director.hosts[victim].peer.conn.partitioned = True
        tick_at_partition = vcore.tick_index
        for _ in range(250):
            rig.pump(1)
            if rig.director.hosts[victim].state == "dead":
                break
        hr = rig.director.hosts[victim]
        assert hr.state == "dead"
        assert hr.epoch == epoch_before + 1  # the fence is the bump
        fo = rig.director.failovers[-1]
        assert fo["host"] == victim and fo["restored_on"] == 1 - victim
        # every re-placed session resumed at the EXACT checkpoint frame
        assert fo["restored"]
        for mid, frames in fo["restored"].items():
            assert fo["checkpoint_frames"][mid] == frames
        # the zombie advanced during the partition...
        assert vcore.tick_index > tick_at_partition

        # ...and on heal, its first control message is rejected and it
        # self-terminates without ever advancing again
        rig.director.hosts[victim].peer.conn.partitioned = False
        for _ in range(400):
            rig.pump(1)
            if vcore.terminated == "fenced":
                break
        assert vcore.terminated == "fenced"
        assert rig.director.hosts[victim].fence_rejections >= 1
        frozen = vcore.tick_index
        rig.pump(20)
        assert vcore.tick_index == frozen  # no double-advance, ever

        # survivors finish; re-placed sessions' checksum histories are
        # gap-free and every match stays bitwise equal to the twin —
        # the zombie's parallel universe never leaked into this one
        surv = rig.agents[1 - victim]
        rig.drive_done(cores=[surv])
        reports = rig.director.collect_reports()
        rep = reports[1 - victim]
        for entry in rep["islands"].values():
            assert entry["desyncs"] == 0
            for hist in entry["histories"].values():
                frames = sorted(int(f) for f in hist)
                gaps = {
                    frames[i + 1] - frames[i]
                    for i in range(len(frames) - 1)
                }
                assert gaps <= {10}  # the desync-interval stride only
        parity = compare_with_twin(specs, reports, {0})
        assert parity["clean_exact"] and parity["faulted_exact"], parity

        # the fleet instruments moved and export through BOTH exporters
        prom = GLOBAL_TELEMETRY.prometheus()
        snap = GLOBAL_TELEMETRY.snapshot()
        for name in (
            "ggrs_fleet_heartbeats_missed_total",
            "ggrs_fleet_host_epoch",
            "ggrs_fleet_failovers_total",
            "ggrs_fleet_failover_ms",
            "ggrs_fleet_fenced_total",
        ):
            assert name in prom
            assert name in snap["metrics"]
        assert snap["metrics"]["ggrs_fleet_failovers_total"]["values"][""] >= 1
        epoch_series = snap["metrics"]["ggrs_fleet_host_epoch"]["values"]
        assert epoch_series[str(victim)] == epoch_before + 1
    finally:
        tel.enabled = False
        tel.reset()


@pytest.mark.slow  # the seize-at-fence corner of the fencing contract;
# test_fencing_contract_end_to_end keeps the contract itself in tier-1
def test_zombie_checkpoint_rewrite_cannot_reach_the_restore(tmp_path):
    """Seize-at-fence: a fenced host rewriting its checkpoint file after
    the fence changes nothing — the director restored from the bytes it
    seized at fencing time."""
    rig = Rig(tmp_path, checkpoint_every=6)
    spec = _spec(0, seed=77, ticks=160)
    victim = rig.director.place_match(spec)
    vcore = rig.agents[victim]
    for _ in range(40):
        rig.pump(1)
    assert vcore.last_checkpoint is not None
    seized_frames = None
    vcore.partition(10_000)  # long: stays a zombie through the test
    rig.director.hosts[victim].peer.conn.partitioned = True
    for _ in range(250):
        rig.pump(1)
        if rig.director.hosts[victim].state == "dead":
            break
    fo = rig.director.failovers[-1]
    seized_frames = fo["checkpoint_frames"]
    # the zombie keeps running and checkpointing PAST the fence...
    ckpts_before = vcore.checkpoints_written
    for _ in range(60):
        vcore.step()
        rig.clock.advance(10)
    assert vcore.checkpoints_written > ckpts_before
    # ...but the restore already happened from the seized bytes
    assert fo["restored"] == seized_frames
    assert fo["checkpoint_frames"] == seized_frames


# ----------------------------------------------------------------------
# rolling upgrade
# ----------------------------------------------------------------------

def test_rolling_upgrade_loses_nothing(tmp_path):
    rig = Rig(tmp_path)
    specs = [_spec(0, seed=900, ticks=96), _spec(1, seed=901, ticks=96)]
    for s in specs:
        rig.director.place_match(s)
    for _ in range(30):
        rig.pump(1)
    before_hist = {}
    for rep in rig.director.collect_reports().values():
        for mid, entry in rep["islands"].items():
            before_hist[mid] = entry["histories"]
    sessions_before = sum(
        hr.sessions for hr in rig.director.hosts.values() if hr.alive()
    )

    def spawn(old_hid):
        rig.add_agent(max_sessions=8, label=f"replacement-{old_hid}")

    ups = rig.director.rolling_upgrade(spawn, register_timeout_ms=30_000)
    assert len(ups) == 2  # both original hosts cycled, one at a time
    assert all(u["exported"] >= 0 for u in ups)
    rig.pump(15)  # let the replacements' heartbeats refresh occupancy
    sessions_after = sum(
        hr.sessions for hr in rig.director.hosts.values() if hr.alive()
    )
    assert sessions_after == sessions_before  # zero sessions lost
    # both old agents drained cleanly (not fenced)
    assert rig.agents[0].terminated == "drained"
    assert rig.agents[1].terminated == "drained"

    new_cores = [c for c in rig.agents if c.terminated is None]
    rig.drive_done(cores=new_cores)
    reports = rig.director.collect_reports()
    merged = {}
    for rep in reports.values():
        merged.update(rep["islands"])
    for mid, entry in merged.items():
        assert entry["desyncs"] == 0
        # zero confirmed frames lost: every pre-upgrade checksum entry
        # survives, byte-identical, in the post-upgrade history
        for peer, hist in before_hist.get(mid, {}).items():
            for f, c in hist.items():
                assert entry["histories"][peer].get(f) == c
    parity = compare_with_twin(specs, reports, set())
    assert parity["clean_exact"], parity


# ----------------------------------------------------------------------
# agent-side quarantine
# ----------------------------------------------------------------------

def test_vanished_lane_quarantines_island_not_agent(tmp_path):
    rig = Rig(tmp_path)
    rig.director.place_match(_spec(0, ticks=64))
    rig.director.place_match(_spec(1, ticks=64))
    for _ in range(10):
        rig.pump(1)
    # simulate an out-of-band detach (the bug class: stale-key collision)
    owner0 = rig.director.matches[0]["host"]
    core = rig.agents[owner0]
    island = core.islands[0]
    core.host.detach(next(iter(island.keys.values())))
    rig.pump(3)
    assert island.failed
    assert core.terminated is None  # the agent lives
    # the sibling match still finishes cleanly
    rig.drive_done()


def test_heartbeat_reconciliation_suspect_export_and_orphans(tmp_path):
    """The agent's island list is ground truth: a suspect-export match
    still hosted flips back to placed; one that vanished (export
    executed, reply lost) is recorded lost; an orphan copy (the match
    table names another owner) is released off the non-owner."""
    rig = Rig(tmp_path, n_agents=1)
    rig.director.place_match(_spec(0, ticks=64))
    rig.pump(10)
    rec = rig.director.matches[0]

    # ambiguous export where the agent still hosts the island: placed
    rec["state"] = "suspect-export"
    rig.pump(10)  # a heartbeat cycle
    assert rec["state"] == "placed"

    # orphan: the table says another host owns match 0, but this agent
    # still reports (and hosts) it -> the copy is torn down
    rec["host"] = 999
    rig.pump(15)
    assert (0, 0) in rig.director.orphans_released
    assert 0 not in rig.agents[0].islands
    rec["host"] = 0  # restore table sanity for the next phase

    # suspect-export whose island is GONE: the ticket died with the
    # lost reply — recorded lost, not parked forever
    rec["state"] = "suspect-export"
    rig.pump(10)
    assert rec["state"] == "lost"
    assert 0 in rig.director.matches_lost


def test_upgrade_rescue_persists_ticket_when_replacement_never_comes(tmp_path):
    """The drained agent exited; its ticket blob is the ONLY copy of
    its sessions. A respawn that never registers must persist the
    ticket for operator replay, mark the matches orphaned, and release
    the admissions hold — never silently lose the sessions."""
    rig = Rig(tmp_path, n_agents=1)
    rig.director.place_match(_spec(0, ticks=64))
    for _ in range(10):
        rig.pump(1)
    with pytest.raises(RpcTimeout):
        rig.director.rolling_upgrade(
            lambda old: None,  # the replacement never comes
            register_timeout_ms=400,
        )
    rescue = os.path.join(str(tmp_path), "upgrade_host0.ckpt")
    assert os.path.exists(rescue)
    from ggrs_tpu.fleet.ticket import peek_ticket, read_ticket_file

    header = peek_ticket(read_ticket_file(rescue))
    assert header["matches"] == [0]
    rec = rig.director.matches[0]
    assert rec["state"] == "orphaned"
    assert rec["orphan_path"] == rescue
    assert rig.director.hosts[0].admissions_held is False
    assert rig.agents[0].terminated == "drained"
