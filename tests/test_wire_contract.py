"""Wire-contract satellite: the runtime encoders against the lint pass's
source-level extraction (analysis/wire_contract.py), closing the loop
from source text to actual bytes — if either side drifts, one of these
fails before a cross-stack packet ever gets the chance to misparse."""

import struct

import pytest

from ggrs_tpu.analysis.wire_contract import extract
from ggrs_tpu.network import messages as M
from ggrs_tpu.network.messages import (
    INPUT_MSG_OVERHEAD,
    MAX_INPUT_PAYLOAD,
    ChecksumReport,
    InputAck,
    InputMsg,
    KeepAlive,
    Message,
    QualityReply,
    QualityReport,
    SyncReply,
    SyncRequest,
    decode_message,
    encode_message,
)
from ggrs_tpu.network.sockets import (
    MAX_DATAGRAM_SIZE,
    RECV_BUFFER_SIZE,
    check_datagram_size,
)
from ggrs_tpu.sync_layer import ConnectionStatus


@pytest.fixture(scope="module")
def contract():
    return extract()


def test_extraction_sees_the_real_constants(contract):
    assert contract["recv_buffer_size"] == RECV_BUFFER_SIZE
    assert contract["max_datagram_size"] == MAX_DATAGRAM_SIZE
    assert contract["max_input_payload"] == MAX_INPUT_PAYLOAD
    assert contract["input_overhead"] == INPUT_MSG_OVERHEAD
    assert contract["udp_max_payload"] == 65507


def test_msg_codes_match_native(contract):
    py, cpp = contract["py_msg_codes"], contract["cpp_msg_codes"]
    assert py and cpp
    assert py == cpp
    # and the runtime module agrees with its own source text
    for name, val in py.items():
        assert getattr(M, name) == val


def test_encoded_sizes_match_extracted_struct_formats(contract):
    sizes = contract["struct_sizes"]
    header = sizes["_HEADER"]
    cases = [
        (SyncRequest(7), header + sizes["_U32"]),
        (SyncReply(9), header + sizes["_U32"]),
        (InputAck(12), header + sizes["_I32"]),
        (QualityReport(-3, 123456), header + sizes["_QUALITY_REPORT"]),
        (QualityReply(123456), header + sizes["_U64"]),
        (ChecksumReport(checksum=(1 << 127) | 5, frame=44),
         header + sizes["_CHECKSUM_REPORT"]),
        (KeepAlive(), header),
    ]
    for body, want in cases:
        wire = encode_message(Message(0xAB, body))
        assert len(wire) == want, type(body).__name__
        # and the codec round-trips its own bytes
        got = decode_message(wire)
        assert got.body == body


def test_input_msg_size_formula(contract):
    sizes = contract["struct_sizes"]
    statuses = [ConnectionStatus(False, 3), ConnectionStatus(True, -1)]
    payload = b"\x01\x02\x03"
    body = InputMsg(
        peer_connect_status=statuses, start_frame=5, ack_frame=2,
        bytes_=payload,
    )
    wire = encode_message(Message(1, body))
    assert len(wire) == (
        sizes["_HEADER"] + sizes["_INPUT_HEAD"]
        + len(statuses) * sizes["_STATUS"] + 2 + len(payload)
    )


def test_worst_case_input_msg_exactly_fills_the_datagram_bound():
    # 16 statuses (the native MAX_HANDLES) + the full payload cap must
    # land EXACTLY on MAX_DATAGRAM_SIZE: heavier would die in sendto(),
    # lighter would mean wasted wire budget hidden in the formula
    statuses = [ConnectionStatus(False, i) for i in range(16)]
    body = InputMsg(
        peer_connect_status=statuses, start_frame=1, ack_frame=0,
        bytes_=b"\xff" * MAX_INPUT_PAYLOAD,
    )
    wire = encode_message(Message(2, body))
    assert len(wire) == MAX_DATAGRAM_SIZE
    assert check_datagram_size(wire) is wire  # the transport accepts it


def test_input_payload_past_the_cap_raises_at_encode():
    from ggrs_tpu.errors import InvalidRequest

    body = InputMsg(bytes_=b"\x00" * (MAX_INPUT_PAYLOAD + 1))
    with pytest.raises(InvalidRequest, match="cap"):
        encode_message(Message(2, body))


def test_input_payload_cap_tightens_past_16_statuses():
    # MAX_INPUT_PAYLOAD assumes the native 16-handle worst case; a wider
    # pure-Python session must tighten the cap by its extra statuses so
    # the encoded datagram never exceeds what the transport carries
    from ggrs_tpu.errors import InvalidRequest

    statuses = [ConnectionStatus(False, i) for i in range(17)]
    over = InputMsg(
        peer_connect_status=statuses, bytes_=b"\x00" * MAX_INPUT_PAYLOAD
    )
    with pytest.raises(InvalidRequest, match="17 connect statuses"):
        encode_message(Message(2, over))
    at_cap = InputMsg(
        peer_connect_status=statuses,
        bytes_=b"\x00" * (MAX_INPUT_PAYLOAD - 5),  # one extra _STATUS
    )
    wire = encode_message(Message(2, at_cap))
    assert len(wire) == MAX_DATAGRAM_SIZE
    assert check_datagram_size(wire) is wire


def test_recv_buffer_bounds_agree_across_stacks(contract):
    # one canonical receive bound, aliased everywhere
    from ggrs_tpu.native import sockets as native_sockets

    assert native_sockets.RECV_BUFFER_SIZE == RECV_BUFFER_SIZE
    assert MAX_DATAGRAM_SIZE == min(RECV_BUFFER_SIZE, 65507)
    assert contract["native_send_buf_cap"] == RECV_BUFFER_SIZE
    assert contract["native_wire_buf_cap"] == RECV_BUFFER_SIZE
    # the runtime modules agree with the source-level extraction
    from ggrs_tpu.native.endpoint import _SEND_BUF_CAP
    from ggrs_tpu.native.session import _WIRE_BUF_CAP

    assert _SEND_BUF_CAP == RECV_BUFFER_SIZE
    assert _WIRE_BUF_CAP == RECV_BUFFER_SIZE


def test_check_datagram_size_rejects_past_bound():
    from ggrs_tpu.errors import InvalidRequest

    assert check_datagram_size(b"x" * MAX_DATAGRAM_SIZE)
    with pytest.raises(InvalidRequest):
        check_datagram_size(b"x" * (MAX_DATAGRAM_SIZE + 1))


def test_header_struct_matches_native_abi(contract):
    # ggrs_native.h structs the ctypes bindings mirror — spot-check the
    # checksum width the wire format and the session ABI must share
    h = contract["h_structs"]
    sess_event = dict(
        (f, (t, n)) for f, t, n in h["ggrs_sess_event"]
    )
    assert sess_event["local_checksum"] == ("uint8_t", 16)
    assert sess_event["remote_checksum"] == ("uint8_t", 16)
    # the Python codec's u128 checksum field is the same 16 bytes
    assert struct.calcsize(contract["struct_formats"]["_CHECKSUM_REPORT"]) \
        == struct.calcsize("<i") + 16
