"""Session-level runs on the native C++ stack: P2P over real loopback UDP
with C++ endpoints and C++ sockets, including a mixed pair (one session
native, the other pure Python) — wire-format interop is the contract."""

import pytest

from ggrs_tpu import (
    AdvanceFrame,
    LoadGameState,
    PlayerType,
    SaveGameState,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.native import available
from stubs import GameStub

pytestmark = pytest.mark.skipif(
    not available(), reason="native library not built (make -C native)"
)

PORT_A, PORT_B = 7921, 7922


def make_session(port, remote_port, local_handle, native):
    b = SessionBuilder(input_size=1).with_num_players(2)
    if native:
        from ggrs_tpu.native.sockets import NativeUdpNonBlockingSocket

        b = b.with_native_endpoints(True)
        sock = NativeUdpNonBlockingSocket(port)
    else:
        from ggrs_tpu.network.sockets import UdpNonBlockingSocket

        sock = UdpNonBlockingSocket(port)
    b.add_player(PlayerType.local(), local_handle)
    b.add_player(PlayerType.remote(("127.0.0.1", remote_port)), 1 - local_handle)
    return b.start_p2p_session(sock)


def run_lockstep(s0, s1, frames=12):
    for _ in range(80):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        if (
            s0.current_state() == SessionState.RUNNING
            and s1.current_state() == SessionState.RUNNING
        ):
            break
    assert s0.current_state() == SessionState.RUNNING
    assert s1.current_state() == SessionState.RUNNING

    g0, g1 = GameStub(), GameStub()
    for f in range(frames):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        s0.add_local_input(0, bytes([f % 5]))
        s1.add_local_input(1, bytes([(f * 2) % 5]))
        g0.handle_requests(s0.advance_frame())
        g1.handle_requests(s1.advance_frame())
    # settle: let the tail inputs arrive and corrections roll back
    for f in range(frames, frames + 4):
        s0.poll_remote_clients()
        s1.poll_remote_clients()
        s0.add_local_input(0, bytes([f % 5]))
        s1.add_local_input(1, bytes([(f * 2) % 5]))
        g0.handle_requests(s0.advance_frame())
        g1.handle_requests(s1.advance_frame())
    # confirmed prefixes must agree exactly
    confirmed = min(max(g0.history) - 2, max(g1.history) - 2, frames)
    for f in range(1, confirmed + 1):
        assert g0.history[f] == g1.history[f], f"divergence at frame {f}"
    return g0, g1


def test_native_p2p_session_over_native_udp():
    s0 = make_session(PORT_A, PORT_B, 0, native=True)
    s1 = make_session(PORT_B, PORT_A, 1, native=True)
    run_lockstep(s0, s1)


def test_mixed_native_python_session_interop():
    s0 = make_session(PORT_A + 10, PORT_B + 10, 0, native=True)
    s1 = make_session(PORT_B + 10, PORT_A + 10, 1, native=False)
    run_lockstep(s0, s1)


def test_native_session_reports_network_stats():
    import time

    from ggrs_tpu import NotSynchronized

    s0 = make_session(PORT_A + 20, PORT_B + 20, 0, native=True)
    s1 = make_session(PORT_B + 20, PORT_A + 20, 1, native=True)
    start = time.monotonic()
    run_lockstep(s0, s1)
    try:
        stats = s0.network_stats(1)  # remote player handle for session 0
        assert stats.send_queue_len >= 0
    except NotSynchronized:
        # parity with the Python endpoint: stats are unavailable within the
        # first second of a session (kbps denominator would be zero)
        assert time.monotonic() - start < 1.5


def test_native_sessions_independent_across_threads():
    """The ABI threading contract's regression gate (ggrs_native.h:
    handles are unsynchronized but fully independent — no shared mutable
    globals): two native P2P sessions, one driven per thread, must run a
    full match concurrently without interference; and a handle CREATED on
    the main thread may be DRIVEN entirely from a worker (the Send half
    of the contract — handles are not thread-affine)."""
    import threading
    import time

    s0 = make_session(19411, 19412, 0, native=True)
    s1 = make_session(19412, 19411, 1, native=True)
    games = {0: GameStub(), 1: GameStub()}
    errors = []
    barrier = threading.Barrier(2)

    def drive(sess, handle):
        try:
            barrier.wait(timeout=10)
            for _ in range(600):
                sess.poll_remote_clients()
                if sess.current_state() == SessionState.RUNNING:
                    break
                time.sleep(0.001)
            assert sess.current_state() == SessionState.RUNNING
            for f in range(30):
                sess.poll_remote_clients()
                sess.add_local_input(handle, bytes([(f * (handle + 2)) % 7]))
                games[handle].handle_requests(sess.advance_frame())
                time.sleep(0.001)
        except Exception as e:  # surfaced below; a thread must not die silently
            errors.append((handle, e))

    threads = [
        threading.Thread(target=drive, args=(s0, 0)),
        threading.Thread(target=drive, args=(s1, 1)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads), "drive thread hung"
    # both peers simulated and their confirmed prefixes agree
    confirmed = min(max(games[0].history) - 3, max(games[1].history) - 3, 25)
    assert confirmed >= 10
    for f in range(1, confirmed + 1):
        assert games[0].history[f] == games[1].history[f], f"frame {f}"
