"""Entity-tiled pallas beam rollout (ggrs_tpu/tpu/pallas_beam.py): the
speculation tax was the beam's broken economics (B*L XLA-scan steps of
device time per tick); the kernel runs the same rollout at fused-kernel
cost. These tests pin the property everything rests on: the pallas
rollout's trajectories and checksums are BIT-IDENTICAL to the XLA
vmap+scan path, so adoption cannot tell which backend speculated."""

import numpy as np
import pytest

import jax
import jax.tree_util as jtu

from ggrs_tpu.models.arena import Arena
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.models.swarm import Swarm
from ggrs_tpu.tpu.resim import ResimCore

P = 2


def make_core(game, spec_backend, seed=3):
    rng = np.random.default_rng(seed)
    core = ResimCore(game, max_prediction=6, num_players=P,
                     spec_backend=spec_backend)
    W = core.window
    for f in range(4):
        inputs = np.zeros((W, P, 1), np.uint8)
        inputs[0] = rng.integers(0, 16, (P, 1))
        statuses = np.zeros((W, P), np.int32)
        slots = np.full((W,), core.scratch_slot, np.int32)
        slots[0] = f % core.ring_len
        core.tick(False, 0, inputs, statuses, slots, 1, start_frame=f)
    return core


def assert_spec_equal(a, b):
    la = jtu.tree_leaves_with_path(jax.device_get(a))
    lb = jtu.tree_leaves(jax.device_get(b))
    assert len(la) == len(lb)
    for (path, x), y in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=jtu.keystr(path)
        )


@pytest.mark.parametrize("Game,mod", [(ExGame, 16), (Swarm, 128), (Arena, 64)])
def test_pallas_rollout_bit_parity_with_xla(Game, mod):
    """Multi-tile rollout (auto tile sizing over 512-1024 entities; arena
    runs the reduction-phase single-tile path): the full speculation
    tuple — trajectories, per-step checksums, anchor checksum — matches
    the XLA path leaf-for-leaf, all three families."""
    game = Game(P, 1024)
    a = make_core(game, "pallas-interpret")
    b = make_core(game, "xla")
    rng = np.random.default_rng(9)
    B, L = 6, 5
    beam_inputs = rng.integers(0, mod, size=(B, L, P, 1), dtype=np.uint8)
    beam_statuses = np.zeros((B, L, P), np.int32)
    assert_spec_equal(
        a.speculate(2, beam_inputs, beam_statuses),
        b.speculate(2, beam_inputs, beam_statuses),
    )


def test_adoption_from_pallas_speculation_matches_resim():
    """End to end: a backend speculating through the pallas kernel adopts
    trajectories that bit-match a plain resimulating backend."""
    from ggrs_tpu import SessionBuilder
    from ggrs_tpu.tpu import TpuRollbackBackend

    def make_backend(bw, spec_backend="xla"):
        return TpuRollbackBackend(
            ExGame(P, 128), max_prediction=6, num_players=P, beam_width=bw,
            spec_backend=spec_backend,
        )

    def make_sess():
        return (
            SessionBuilder(input_size=1)
            .with_num_players(P)
            .with_max_prediction_window(6)
            .with_check_distance(3)
            .start_synctest_session()
        )

    beam = make_backend(8, "pallas-interpret")
    plain = make_backend(0)
    sb, sp = make_sess(), make_sess()
    for t in range(30):
        for h in range(P):
            sb.add_local_input(h, bytes([4 + h]))
            sp.add_local_input(h, bytes([4 + h]))
        beam.handle_requests(sb.advance_frame())
        plain.handle_requests(sp.advance_frame())
    a, b = beam.state_numpy(), plain.state_numpy()
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=k)
    assert beam.beam_hits > 0  # the pallas-speculated path actually adopted


def test_non_confirmed_statuses_fall_back_to_xla():
    """Rollouts with any non-CONFIRMED status bypass the pallas kernel
    (which bakes the all-CONFIRMED contract in) and still work."""
    from ggrs_tpu.types import InputStatus

    game = ExGame(P, 256)
    core = make_core(game, "pallas-interpret")
    rng = np.random.default_rng(11)
    B, L = 4, 4
    beam_inputs = rng.integers(0, 16, size=(B, L, P, 1), dtype=np.uint8)
    beam_statuses = np.full(
        (B, L, P), int(InputStatus.DISCONNECTED), np.int32
    )
    traj, his, los, a_hi, a_lo = core.speculate(2, beam_inputs, beam_statuses)
    assert np.asarray(his).shape == (B, L)

    # and the XLA oracle agrees with itself through the same entry point
    xla = make_core(game, "xla")
    assert_spec_equal(
        core.speculate(2, beam_inputs, beam_statuses),
        xla.speculate(2, beam_inputs, beam_statuses),
    )


def test_non_tileable_model_auto_falls_back():
    """On a non-TPU platform auto always resolves to XLA (arena included —
    its reduction-phase pallas path is opt-in via -interpret in tests)."""
    core = ResimCore(Arena(P, 256), max_prediction=6, num_players=P,
                     spec_backend="auto")
    assert core.spec_backend == "xla"


def test_oversized_reduce_rollout_falls_back_to_xla():
    """A reduction-phase rollout whose B*L trajectory windows exceed the
    single-tile budget demotes the core to the XLA speculation path with a
    warning — same speculate() results as a plain-XLA core, no crash."""
    # 65536 entities x B=16 x L windows is far past the 96MB envelope
    game = Arena(P, 65536)
    core = make_core(game, "pallas")
    rng = np.random.default_rng(4)
    B, L = 16, 3
    beam_inputs = rng.integers(0, 64, size=(B, L, P, 1), dtype=np.uint8)
    beam_statuses = np.zeros((B, L, P), np.int32)
    with pytest.warns(UserWarning, match="pallas beam rollout unavailable"):
        spec = core.speculate(1, beam_inputs, beam_statuses)
    assert core.spec_backend == "xla"  # demoted permanently
    xla = make_core(Arena(P, 65536), "xla")
    assert_spec_equal(spec, xla.speculate(1, beam_inputs, beam_statuses))
