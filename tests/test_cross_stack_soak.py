"""Cross-stack loopback-UDP soak (VERDICT r1 item 6).

The reference proves P2P liveness with two same-implementation sessions
over real loopback UDP (tests/test_p2p_session.rs:67-95). Here the pair is
CROSS-IMPLEMENTATION — one pure-Python stack, one full C++ native stack
(session core + endpoints + socket) — so the soak certifies wire-format
and protocol-semantics interop end to end on real sockets, with desync
detection as the bit-parity referee. A second soak rides the authenticated
transport (SipHash MAC + anti-replay) on both peers. Runs against
whichever native build is current, including `make -C native sanitize`
(UBSAN) — the CI recipe is: make sanitize && pytest this file && make.
"""

import time

import pytest

from ggrs_tpu import (
    DesyncDetected,
    DesyncDetection,
    PlayerType,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.native import available
from stubs import GameStub

pytestmark = pytest.mark.skipif(
    not available(), reason="native library not built (make -C native)"
)

KEY = bytes(range(16))


def build_pair(port_a, port_b, auth=False):
    """Session A: pure Python stack. Session B: full native stack."""
    from ggrs_tpu.native.sockets import NativeUdpNonBlockingSocket
    from ggrs_tpu.network.auth import AuthenticatedSocket
    from ggrs_tpu.network.sockets import UdpNonBlockingSocket

    def base(handle, other_port):
        return (
            SessionBuilder(input_size=1)
            .with_num_players(2)
            .with_max_prediction_window(8)
            .with_desync_detection_mode(DesyncDetection.on(interval=20))
            .add_player(PlayerType.local(), handle)
            .add_player(
                PlayerType.remote(("127.0.0.1", other_port)), 1 - handle
            )
        )

    sock_a = UdpNonBlockingSocket(port_a)
    if auth:
        sock_a = AuthenticatedSocket(sock_a, KEY, replay_protect=True)
    sess_a = base(0, port_b).start_p2p_session(sock_a)

    # the native session core drives the Python-visible socket seam, so the
    # authenticated wrapper composes the same way on the native stack
    b = base(1, port_a).with_native_sessions(True)
    sock_b = NativeUdpNonBlockingSocket(port_b) if not auth else (
        AuthenticatedSocket(UdpNonBlockingSocket(port_b), KEY, replay_protect=True)
    )
    sess_b = b.start_p2p_session(sock_b)
    return sess_a, sess_b


def soak(sess_a, sess_b, frames):
    for _ in range(300):
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        sess_a.events()
        sess_b.events()
        if (
            sess_a.current_state() == SessionState.RUNNING
            and sess_b.current_state() == SessionState.RUNNING
        ):
            break
        time.sleep(0.002)
    assert sess_a.current_state() == SessionState.RUNNING, "handshake failed"
    assert sess_b.current_state() == SessionState.RUNNING

    ga, gb = GameStub(), GameStub()
    desyncs = []
    for f in range(frames):
        sess_a.poll_remote_clients()
        desyncs += [e for e in sess_a.events() if isinstance(e, DesyncDetected)]
        sess_a.add_local_input(0, bytes([(f * 3 + 1) % 13]))
        ga.handle_requests(sess_a.advance_frame())

        sess_b.poll_remote_clients()
        desyncs += [e for e in sess_b.events() if isinstance(e, DesyncDetected)]
        sess_b.add_local_input(1, bytes([(f * 7 + 2) % 13]))
        gb.handle_requests(sess_b.advance_frame())
        if f % 8 == 0:
            time.sleep(0.001)  # let the kernel's loopback queue breathe

    # drain in-flight inputs and checksum reports, then one final advance
    for _ in range(40):
        sess_a.poll_remote_clients()
        sess_b.poll_remote_clients()
        desyncs += [e for e in sess_a.events() if isinstance(e, DesyncDetected)]
        desyncs += [e for e in sess_b.events() if isinstance(e, DesyncDetected)]
        time.sleep(0.001)
    sess_a.add_local_input(0, b"\x00")
    ga.handle_requests(sess_a.advance_frame())
    sess_b.add_local_input(1, b"\x00")
    gb.handle_requests(sess_b.advance_frame())

    assert not desyncs, f"cross-stack desync: {desyncs[:3]}"
    confirmed = min(sess_a.confirmed_frame(), sess_b.confirmed_frame())
    assert confirmed > frames // 2, f"confirmed only {confirmed}/{frames}"
    for f in range(1, confirmed + 1):
        assert ga.history[f] == gb.history[f], f"replicas diverged at frame {f}"
    return confirmed


def test_cross_stack_udp_soak():
    sess_a, sess_b = build_pair(17941, 17942)
    confirmed = soak(sess_a, sess_b, frames=200)
    assert confirmed > 150


def test_cross_stack_udp_soak_authenticated():
    sess_a, sess_b = build_pair(17943, 17944, auth=True)
    soak(sess_a, sess_b, frames=120)
