"""Speculative bubble-filling: bitwise parity of a speculating host vs
a never-speculating twin.

The contract under test is the ISSUE's acceptance surface: a
`SessionHost(speculation=True)` fed the same seeded starved traffic as a
`speculation=False` twin must land on bit-identical per-session checksum
histories, stacked device state and ring bytes in EVERY arrival pattern
— full prefix hit (the drafted future was right: the tick is served
from the draft via the adopt route), partial prefix (truncate to the
longest-correct prefix, resimulate the suffix), and total miss (the
draft is discarded, the normal rollback path runs untouched). Input
starvation is forced the way WAN outages force it: per-match blackhole
windows longer than the prediction window on a lossy in-memory mesh.

Also pinned here: the draft/adopt jit programs are warmup-compiled and
the cache stays frozen within dispatch_bucket_budget() under the
sanitizer, and the four speculation instruments flow through both
registry-driven exporters and host.telemetry().
"""

import random

import numpy as np
import pytest

import jax

from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.obs import GLOBAL_TELEMETRY
from ggrs_tpu.serve import SessionHost
from ggrs_tpu.serve.loadgen import (
    build_matches,
    drive_scripted,
    held_scripts,
    starve_on_tick,
    sync_fleet,
)
from ggrs_tpu.serve.speculation import SpeculationPlanner
from ggrs_tpu.utils.clock import FakeClock

ENTITIES = 16


def _assert_tree_equal(ta, tb, what):
    la = jax.tree_util.tree_leaves_with_path(ta)
    lb = jax.tree_util.tree_leaves(tb)
    assert len(la) == len(lb)
    for (path, a), b in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"{what}{jax.tree_util.keystr(path)}",
        )


def run_starved(scripts_fn, *, speculation, sessions=4, ticks=90,
                hole_every=30, hole_len=12, seed=7, loss=0.0,
                mesh=None, **host_kw):
    """One hosted fleet under blackhole-forced input starvation: peer 0
    of every match goes dark for `hole_len` ticks every `hole_every`
    ticks (longer than the prediction window, so the other peers starve
    at the gate), inputs scripted per (match, peer, tick)."""
    clock = FakeClock()
    net = InMemoryNetwork(
        clock, latency_ms=16, jitter_ms=4, loss=loss, seed=seed
    )
    host = SessionHost(
        ExGame(num_players=4, num_entities=ENTITIES),
        max_prediction=8, num_players=4, max_sessions=sessions + 4,
        clock=clock, idle_timeout_ms=0, speculation=speculation,
        mesh=mesh, **host_kw,
    )
    matches = build_matches(host, net, clock, sessions=sessions, seed=seed)
    sync_fleet(host, matches, clock)
    scripts = scripts_fn(matches, ticks, seed)
    drive_scripted(
        host, matches, clock, scripts, ticks,
        on_tick=starve_on_tick(
            net, matches, hole_every=hole_every, hole_len=hole_len
        ),
    )
    host.device.block_until_ready()
    return host, [k for keys in matches for k in keys]


def assert_bitwise_twin(host_on, keys_on, host_off, keys_off):
    """The full parity surface: per-session frames + checksum
    histories, canonical stacked state and ring bytes, and the explicit
    whole-fleet checksum pass."""
    for ka, kb in zip(keys_on, keys_off):
        sa, sb = host_on.session(ka), host_off.session(kb)
        assert sa.current_frame == sb.current_frame > 0
        assert sa.local_checksum_history == sb.local_checksum_history
        assert len(sa.local_checksum_history) > 0  # non-vacuous
    r_on, s_on = host_on.device.stacked_canonical()
    r_off, s_off = host_off.device.stacked_canonical()
    _assert_tree_equal(s_on, s_off, "states")
    _assert_tree_equal(r_on, r_off, "rings")
    hi_a, lo_a = host_on.device.checksum_slots()
    hi_b, lo_b = host_off.device.checksum_slots()
    np.testing.assert_array_equal(hi_a, hi_b)
    np.testing.assert_array_equal(lo_a, lo_b)
    assert host_on.desyncs_observed == host_off.desyncs_observed == 0


# ----------------------------------------------------------------------
# input script shapes
# ----------------------------------------------------------------------


def constant_scripts(matches, ticks, seed):
    """Every player holds one value forever: repeat-last predictions are
    always right, so every starved stall ends in a no-rollback recovery
    — the lineage member's deterministic FULL HIT."""
    return {
        (m, k): [17 + 3 * m + k] * ticks
        for m, keys in enumerate(matches)
        for k in range(len(keys))
    }


def adversarial_scripts(matches, ticks, seed):
    """Fresh pseudorandom value every tick: unlearnable, so drafted
    guesses are wrong at the first corrected frame — TOTAL MISSES."""
    out = {}
    for m, keys in enumerate(matches):
        for k in range(len(keys)):
            rng = random.Random(seed * 997 + m * 31 + k)
            out[(m, k)] = [rng.randrange(1, 250) for _ in range(ticks)]
    return out


# held_scripts comes from loadgen: THE traffic shape the bench arm and
# the smoke starve against — the parity this suite pins must cover the
# same streams those gates measure


class VerifyRecorder:
    """Records every SpeculationPlanner.verify outcome (matched, count)
    so tests can assert which arrival patterns actually occurred."""

    def __init__(self):
        self.outcomes = []

    def install(self, monkeypatch):
        orig = SpeculationPlanner.verify
        rec = self

        def wrapped(self, key, **kw):
            out = orig(self, key, **kw)
            rec.outcomes.append(
                (out[3] if out is not None else 0, kw["count"])
            )
            return out

        monkeypatch.setattr(SpeculationPlanner, "verify", wrapped)
        return self

    def full_hits(self):
        return [o for o in self.outcomes if o[0] == o[1] and o[0] > 0]

    def partials(self):
        return [o for o in self.outcomes if 0 < o[0] < o[1]]

    def misses(self):
        return [o for o in self.outcomes if o[0] == 0]


# ----------------------------------------------------------------------
# the three arrival patterns, bitwise vs the twin
# ----------------------------------------------------------------------


def test_full_hit_parity(monkeypatch):
    """Constant inputs: every stall ends in a no-rollback recovery the
    lineage member serves whole — frames flow from the draft (adopt
    route), zero misses, and the speculating host is bit-identical to
    the never-speculating twin."""
    rec = VerifyRecorder().install(monkeypatch)
    host_on, keys_on = run_starved(constant_scripts, speculation=True)
    host_off, keys_off = run_starved(constant_scripts, speculation=False)
    sec = host_on._spec.section()
    assert host_on.frames_served_from_speculation > 0
    # the planner's own miss counter: zero genuine mispredictions (the
    # recorder's zero rows are draft-window exhaustions, not misses)
    assert sec["adopts"] > 0 and sec["misses"] == 0
    assert rec.full_hits()
    assert host_on.spec_hit_rate > 0.0
    assert host_off.frames_served_from_speculation == 0
    assert_bitwise_twin(host_on, keys_on, host_off, keys_off)


def test_total_miss_parity(monkeypatch):
    """Unlearnable per-tick random inputs: drafts can only miss — the
    normal rollback path serves every arrival and the twin parity
    still holds bitwise."""
    rec = VerifyRecorder().install(monkeypatch)
    host_on, keys_on = run_starved(adversarial_scripts, speculation=True)
    host_off, keys_off = run_starved(adversarial_scripts, speculation=False)
    sec = host_on._spec.section()
    assert sec["frames_drafted"] > 0
    assert sec["misses"] > 0
    assert_bitwise_twin(host_on, keys_on, host_off, keys_off)


def test_partial_prefix_parity(monkeypatch):
    """Hold/switch streams across a lossy mesh: among the arrivals are
    PARTIAL prefix hits (a timing bet matched the first corrected
    frames, then diverged — the adopt serves the prefix and resimulates
    the suffix) and the twins still match bit for bit."""
    rec = VerifyRecorder().install(monkeypatch)
    host_on, keys_on = run_starved(
        held_scripts, speculation=True, sessions=7, ticks=150,
        loss=0.02, seed=11,
    )
    host_off, keys_off = run_starved(
        held_scripts, speculation=False, sessions=7, ticks=150,
        loss=0.02, seed=11,
    )
    assert host_on.frames_served_from_speculation > 0
    assert rec.partials(), (
        f"no partial-prefix adoption occurred (outcomes: {rec.outcomes})"
    )
    assert_bitwise_twin(host_on, keys_on, host_off, keys_off)


# ----------------------------------------------------------------------
# jit discipline + instruments
# ----------------------------------------------------------------------


def test_jit_cache_frozen_after_warmup():
    """Speculation's draft/adopt programs are warmup-compiled on the
    bucket grid: the starved serve afterwards compiles NOTHING (the
    sanitizer turns any post-warmup compile into a hard failure) and
    every dispatch-function cache stays within
    dispatch_bucket_budget() — which counts the two extra speculative
    programs per row bucket."""
    from ggrs_tpu.analysis.sanitize import (
        install_sanitizer,
        uninstall_sanitizer,
    )

    san = install_sanitizer()
    try:
        host, keys = run_starved(
            held_scripts, speculation=True, warmup=True,
        )
        assert not san.recompiles, (
            "post-warmup recompile on the speculating host:\n"
            + "\n".join(e.render() for e in san.recompiles)
        )
        dev = host.device
        assert dev.drafts_launched > 0  # the draft program actually ran
        cache = sum(
            fn._cache_size() for fn in dev._budget_fns().values()
        )
        assert cache <= dev.dispatch_bucket_budget()
        base = len(dev.buckets) * (len(dev.depth_buckets) + 1)
        assert dev.dispatch_bucket_budget() == base + 2 * len(dev.buckets)
    finally:
        uninstall_sanitizer()


def test_spec_instruments_through_exporters():
    """The four speculation instruments are registry-driven: one
    starved speculating run populates them in the snapshot exporter,
    the Prometheus text exporter, AND the host telemetry section's
    speculation block (hit rate included)."""
    from ggrs_tpu import enable_global_telemetry

    enable_global_telemetry()
    try:
        host, keys = run_starved(constant_scripts, speculation=True)
        assert host.frames_served_from_speculation > 0
        snap = host.telemetry()
        m = snap["metrics"]
        for name in (
            "ggrs_spec_frames_drafted_total",
            "ggrs_spec_frames_adopted_total",
            "ggrs_spec_frames_discarded_total",
        ):
            assert m[name]["type"] == "counter", name
        drafted = next(iter(
            m["ggrs_spec_frames_drafted_total"]["values"].values()
        ))
        adopted = next(iter(
            m["ggrs_spec_frames_adopted_total"]["values"].values()
        ))
        assert drafted > 0 and 0 < adopted <= drafted
        hist = m["ggrs_spec_prefix_len"]
        assert hist["type"] == "histogram"
        assert next(iter(hist["values"].values()))["count"] > 0
        spec = snap["host"]["speculation"]
        assert spec["frames_adopted"] == adopted
        assert spec["hit_rate"] > 0.0
        prom = GLOBAL_TELEMETRY.prometheus()
        assert "ggrs_spec_frames_drafted_total" in prom
        assert "ggrs_spec_frames_adopted_total" in prom
        assert "ggrs_spec_frames_discarded_total" in prom
        assert "ggrs_spec_prefix_len_bucket" in prom
    finally:
        GLOBAL_TELEMETRY.enabled = False
        GLOBAL_TELEMETRY.reset()


def test_non_speculating_host_untouched():
    """speculation=False (the default) builds no planner, reports zero
    frames served, and its telemetry host section has no speculation
    block — old readers stay compatible."""
    host, keys = run_starved(
        constant_scripts, speculation=False, ticks=40, hole_every=0,
    )
    assert host._spec is None
    assert host.frames_served_from_speculation == 0
    assert host.spec_hit_rate == 0.0
    assert "speculation" not in host._host_section()
    base = len(host.device.buckets) * (len(host.device.depth_buckets) + 1)
    assert host.device.dispatch_bucket_budget() == base


def test_speculation_requires_statuses_contract():
    """The adopt route replays drafts rolled out with all-CONFIRMED
    statuses — a game that hasn't declared statuses_contract =
    'disconnect-only' must be rejected at host construction."""

    class OpaqueGame(ExGame):
        statuses_contract = None

    with pytest.raises(ValueError, match="statuses_contract"):
        SessionHost(
            OpaqueGame(num_players=2, num_entities=ENTITIES),
            max_prediction=8, num_players=2, max_sessions=4,
            clock=FakeClock(), speculation=True,
        )


# ----------------------------------------------------------------------
# sharded host
# ----------------------------------------------------------------------


def test_sharded_speculation_parity():
    """Speculation on the session-mesh host (drafts respect slot->shard
    affinity): the sharded speculating fleet adopts frames and stays
    bit-identical to the single-device NON-speculating twin."""
    from ggrs_tpu.parallel.mesh import make_session_mesh
    from ggrs_tpu.tpu.backend import ShardedMultiSessionDeviceCore

    mesh = make_session_mesh(8)
    host_on, keys_on = run_starved(
        constant_scripts, speculation=True, mesh=mesh,
    )
    assert isinstance(host_on.device, ShardedMultiSessionDeviceCore)
    host_off, keys_off = run_starved(constant_scripts, speculation=False)
    assert host_on.frames_served_from_speculation > 0
    assert_bitwise_twin(host_on, keys_on, host_off, keys_off)
