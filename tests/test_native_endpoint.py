"""C++ reliability endpoint (native/endpoint.cpp) driven through the same
scenarios as the Python PeerEndpoint, including MIXED pairs (one native, one
Python peer on the same virtual network) — the wire format is the contract.
"""

import random

import pytest

from ggrs_tpu.frame_info import PlayerInput
from ggrs_tpu.native import available
from ggrs_tpu.network.protocol import (
    NUM_SYNC_PACKETS,
    EvDisconnected,
    EvInput,
    EvNetworkInterrupted,
    EvNetworkResumed,
    EvSynchronized,
    PeerEndpoint,
)
from ggrs_tpu.network.sockets import InMemoryNetwork
from ggrs_tpu.sync_layer import ConnectionStatus
from ggrs_tpu.utils.clock import FakeClock

pytestmark = pytest.mark.skipif(
    not available(), reason="native library not built (make -C native)"
)


def make_endpoint(kind, handles, peer_addr, clock, seed, **overrides):
    if kind == "native":
        from ggrs_tpu.native.endpoint import NativePeerEndpoint as cls
    else:
        cls = PeerEndpoint
    kwargs = dict(
        num_players=2,
        local_players=1,
        max_prediction=8,
        disconnect_timeout_ms=2000,
        disconnect_notify_start_ms=500,
        fps=60,
        input_size=1,
        clock=clock,
        rng=random.Random(seed),
    )
    kwargs.update(overrides)
    return cls(handles=handles, peer_addr=peer_addr, **kwargs)


def pump(pairs, status, clock, steps=1, advance_ms=10):
    events = {id(ep): [] for ep, _ in pairs}
    for _ in range(steps):
        for ep, sock in pairs:
            for _, msg in sock.receive_all_messages():
                ep.handle_message(msg)
            events[id(ep)].extend(ep.poll(status))
            ep.send_all_messages(sock)
        clock.advance(advance_ms)
    return events


def make_pair(kind_a, kind_b, clock, net, **overrides):
    sock_a, sock_b = net.socket("a"), net.socket("b")
    ep_a = make_endpoint(kind_a, [1], "b", clock, seed=1, **overrides)
    ep_b = make_endpoint(kind_b, [0], "a", clock, seed=2, **overrides)
    return (ep_a, sock_a), (ep_b, sock_b)


PAIRINGS = [("native", "native"), ("native", "python"), ("python", "native")]


@pytest.mark.parametrize("kind_a,kind_b", PAIRINGS)
def test_handshake_all_pairings(kind_a, kind_b):
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair(kind_a, kind_b, clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    events = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock,
                  steps=2 * NUM_SYNC_PACKETS)
    assert ep_a.is_running() and ep_b.is_running()
    for ep in (ep_a, ep_b):
        assert any(isinstance(e, EvSynchronized) for e in events[id(ep)])


@pytest.mark.parametrize("kind_a,kind_b", PAIRINGS)
def test_input_exchange_all_pairings(kind_a, kind_b):
    """Inputs flow both ways with correct frames/bytes across implementations."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair(kind_a, kind_b, clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2 * NUM_SYNC_PACKETS)
    assert ep_a.is_running() and ep_b.is_running()

    # ep_a's remote is player 1 (b's local player); ep_b's remote is player 0
    got_a, got_b = [], []
    for frame in range(20):
        ep_a.send_input({0: PlayerInput(frame, bytes([frame % 11]))}, status)
        ep_b.send_input({1: PlayerInput(frame, bytes([(frame * 3) % 11]))}, status)
        ev = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock)
        got_a += [e for e in ev[id(ep_a)] if isinstance(e, EvInput)]
        got_b += [e for e in ev[id(ep_b)] if isinstance(e, EvInput)]

    # a's received inputs are attributed to its remote handle (1), b's to 0
    assert [e.player for e in got_a] == [1] * len(got_a)
    assert [e.player for e in got_b] == [0] * len(got_b)
    assert [e.input.frame for e in got_a] == list(range(len(got_a)))
    assert len(got_a) >= 19 and len(got_b) >= 19
    for e in got_a:
        assert e.input.buf == bytes([(e.input.frame * 3) % 11])
    for e in got_b:
        assert e.input.buf == bytes([e.input.frame % 11])


def test_native_reliability_under_loss_and_jitter():
    """30% loss + jitter + duplicates: the resend protocol must still deliver
    every input to a native receiver."""
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=20, jitter_ms=15, loss=0.3,
                          duplicate=0.2, seed=7)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair("native", "native", clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    # lossy handshake: each retry costs a 200ms timer tick, so give it time
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=200, advance_ms=25)
    assert ep_a.is_running() and ep_b.is_running()

    got_b = []
    for frame in range(40):
        ep_a.send_input({1: PlayerInput(frame, bytes([frame % 13]))}, status)
        ev = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2)
        got_b += [e for e in ev[id(ep_b)] if isinstance(e, EvInput)]
    # drain stragglers
    ev = pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=30)
    got_b += [e for e in ev[id(ep_b)] if isinstance(e, EvInput)]

    frames = [e.input.frame for e in got_b]
    assert frames == sorted(frames)  # in order, no gaps skipped
    assert frames == list(range(40))
    for e in got_b:
        assert e.input.buf == bytes([e.input.frame % 13])


def test_native_disconnect_detection_timers():
    """Silence after sync: interrupted at notify_start, disconnected at
    timeout — exact FakeClock semantics as the Python endpoint."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair("native", "native", clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2 * NUM_SYNC_PACKETS)
    assert ep_a.is_running()

    # b goes silent; a keeps polling
    events = []
    for _ in range(60):
        for _, msg in sock_a.receive_all_messages():
            ep_a.handle_message(msg)
        events += ep_a.poll(status)
        ep_a.send_all_messages(sock_a)
        clock.advance(50)
    assert any(isinstance(e, EvNetworkInterrupted) for e in events)
    assert any(isinstance(e, EvDisconnected) for e in events)

    # traffic resumes -> NetworkResumed (before the disconnect timeout only;
    # here we just check the resumed event fires on any new packet)
    ep_b.send_all_messages(sock_b)


def test_native_network_resumed_event():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair("native", "native", clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2 * NUM_SYNC_PACKETS)

    # silence past notify_start but before timeout
    events = []
    for _ in range(12):
        events += ep_a.poll(status)
        clock.advance(50)
    assert any(isinstance(e, EvNetworkInterrupted) for e in events)
    assert not any(isinstance(e, EvDisconnected) for e in events)

    # b speaks again
    ep_b.send_input({0: PlayerInput(0, b"\x05")}, status)
    ep_b.send_all_messages(sock_b)
    clock.advance(10)
    for _, msg in sock_a.receive_all_messages():
        ep_a.handle_message(msg)
    events = ep_a.poll(status)
    assert any(isinstance(e, EvNetworkResumed) for e in events)


def test_native_checksum_report_intake():
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair("native", "python", clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2 * NUM_SYNC_PACKETS)

    big = (1 << 100) + 12345  # u128-range checksum survives the wire
    ep_a.send_checksum_report(50, big)
    ep_a.send_checksum_report(60, 7)
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock)
    assert ep_b.checksum_history == {50: big, 60: 7}

    ep_b.send_checksum_report(70, big * 2 + 1)
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2)
    assert ep_a.checksum_history == {70: big * 2 + 1}


def test_native_network_stats_and_frame_advantage():
    clock = FakeClock()
    net = InMemoryNetwork(clock, latency_ms=30)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair("native", "native", clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=30, advance_ms=40)
    assert ep_a.is_running()

    for frame in range(10):
        ep_a.send_input({1: PlayerInput(frame, b"\x01")}, status)
        ep_b.send_input({0: PlayerInput(frame, b"\x02")}, status)
        ep_a.update_local_frame_advantage(frame)
        ep_b.update_local_frame_advantage(frame)
        pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, advance_ms=220)

    stats = ep_a.network_stats()
    assert stats.ping_ms > 0  # RTT measured via quality report/reply
    assert stats.kbps_sent >= 0
    assert isinstance(ep_a.average_frame_advantage(), int)


def test_native_pending_overflow_disconnects():
    """129 unacked inputs (peer silent) => EvDisconnected, like the
    reference's spectator-overflow rule (protocol.rs:459-463)."""
    clock = FakeClock()
    net = InMemoryNetwork(clock)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair("native", "native", clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2 * NUM_SYNC_PACKETS)

    events = []
    for frame in range(130):
        ep_a.send_input({1: PlayerInput(frame, bytes([frame % 5]))}, status)
        events += ep_a.poll(status)
        # never deliver to b, never ack
    assert any(isinstance(e, EvDisconnected) for e in events)


def test_native_magic_filter_rejects_strangers():
    """After sync, packets with a foreign magic must be ignored."""
    from ggrs_tpu.network.messages import ChecksumReport, Message

    clock = FakeClock()
    net = InMemoryNetwork(clock)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair("native", "native", clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2 * NUM_SYNC_PACKETS)

    stranger_magic = (ep_b.magic + 1) % 65536 or 1
    ep_a.handle_message(Message(stranger_magic, ChecksumReport(checksum=1, frame=5)))
    assert ep_a.checksum_history == {}
    ep_a.handle_message(Message(ep_b.magic, ChecksumReport(checksum=1, frame=5)))
    assert ep_a.checksum_history == {5: 1}


def test_native_survives_crafted_packets():
    """Network-controlled fields must never abort the process: a pong from
    the future, an input window starting beyond last_recv+1, and truncated
    bodies are all dropped or clamped."""
    from ggrs_tpu.network.messages import (
        InputMsg, Message, QualityReply, encode_message,
    )

    clock = FakeClock(start_ms=1000)
    net = InMemoryNetwork(clock)
    (ep_a, sock_a), (ep_b, sock_b) = make_pair("native", "native", clock, net)
    status = [ConnectionStatus(), ConnectionStatus()]
    ep_a.synchronize()
    ep_b.synchronize()
    pump([(ep_a, sock_a), (ep_b, sock_b)], status, clock, steps=2 * NUM_SYNC_PACKETS)
    assert ep_a.is_running()

    # pong far in the future -> RTT clamps to 0, no crash
    ep_a.handle_message(Message(ep_b.magic, QualityReply(pong=(1 << 63))))
    assert ep_a.network_stats is not None  # still alive

    # input window starting far ahead -> dropped, no crash
    ep_a.handle_message(
        Message(ep_b.magic, InputMsg(start_frame=1000, ack_frame=-1, bytes_=b""))
    )

    # truncated wire bytes -> decode rejected, no crash
    wire = encode_message(Message(ep_b.magic, QualityReply(pong=5)))
    ep_a.handle_wire(wire[:4])
    assert ep_a.is_running()


def test_native_endpoint_rejects_over_limit_config():
    from ggrs_tpu.errors import InvalidRequest
    from ggrs_tpu.native.endpoint import NativePeerEndpoint

    with pytest.raises(InvalidRequest):
        NativePeerEndpoint(
            handles=list(range(17)), peer_addr="x", num_players=17,
            local_players=1, max_prediction=8, disconnect_timeout_ms=2000,
            disconnect_notify_start_ms=500, fps=60, input_size=1,
        )
    with pytest.raises(InvalidRequest):
        NativePeerEndpoint(
            handles=[0], peer_addr="x", num_players=2, local_players=1,
            max_prediction=8, disconnect_timeout_ms=2000,
            disconnect_notify_start_ms=500, fps=60, input_size=65,
        )
