"""Multi-process fleet acceptance: real processes, real SIGKILLs, real
sockets. Slow by construction (agent subprocesses pay a jax import and
a warmup compile each) — the fast deterministic coverage of the same
machinery lives in tests/test_fleet_control.py, and scripts/check.sh
--fleet-smoke runs a smaller instance of exactly this soak as a gate.
"""

import pytest

pytestmark = pytest.mark.slow


def test_process_chaos_soak_two_sigkills_partition_and_twin_parity(tmp_path):
    """THE acceptance soak: director + 2 agent processes on loopback,
    two real SIGKILLs (the fleet respawns between them), one
    control-plane partition while the data plane keeps ticking, one
    live cross-process migration, delayed+duplicated director RPCs —
    and at the end, bitwise state/checksum-history parity against the
    single-process twin for every match, faulted ones included."""
    from ggrs_tpu.fleet.chaos import run_process_chaos

    rep = run_process_chaos(
        agents=2, matches=3, players=2, ticks=360, entities=4,
        seed=7, kills=2, rpc_delay_ms=250, rpc_dup=1, migrations=1,
        checkpoint_every=24, warmup=True, base_dir=str(tmp_path),
        respawn=True, drive_timeout_s=420,
    )
    director = rep.pop("_director")

    # two REAL kills happened and both recovered
    assert len(rep["kills"]) == 2
    assert len(rep["failovers"]) >= 2
    for fo in rep["failovers"]:
        assert fo["restored_on"] is not None, fo
        assert fo["lost"] == [], fo
    # every re-placed session resumed at the EXACT checkpoint frame
    assert rep["restore_frame_exact"]
    assert rep["lost_matches"] == []

    # zero desyncs among survivors, with real comparisons behind it
    assert rep["desyncs"] == 0
    assert rep["checksums_compared"] > 0

    # the control partition did not stall the data plane
    assert len(rep["partitions"]) == 1
    assert rep["partitions"][0]["advanced_during"] is True

    # a live migration moved a match between agent processes
    assert any("to" in m for m in rep["migrations"])

    # bitwise parity vs the single-process twin — unfaulted AND
    # kill-restored matches (the restore replays the checkpoint's
    # pickled instant with identical draws, so even the faulted arm
    # converges to the twin's exact bytes)
    parity = rep["parity"]
    assert parity["clean_exact"], parity
    assert parity["faulted_exact"], parity
    for verdict in parity["matches"].values():
        assert verdict["status"] == "ok", parity

    # process hygiene: SIGKILLed agents show the signal, survivors shut
    # down clean (None = the in-flight respawn reaped by the harness)
    codes = rep["agent_exit_codes"]
    assert codes.count(-9) == 2
    assert all(c in (-9, 0, None, 86) for c in codes)
    section = director.section()
    assert section["failovers"] >= 2


def test_process_rolling_upgrade_across_two_agent_processes(tmp_path):
    """Rolling upgrade with REAL processes: drain → respawn (a fresh
    `python -m ggrs_tpu.fleet.agent`) → re-adopt, one host at a time,
    while the matches are mid-flight. Zero sessions lost, zero
    confirmed frames lost (every pre-upgrade checksum-history entry
    survives byte-identical), zero desyncs."""
    import time

    from ggrs_tpu.fleet.chaos import _spawn_agent
    from ggrs_tpu.fleet.director import Director
    from ggrs_tpu.fleet.island import MatchSpec

    base = str(tmp_path)
    director = Director(base_dir=base, seed=3, hb_interval_ms=250,
                        suspicion_misses=8)
    port = director.listen()
    spawn_kw = dict(
        port=port, base_dir=base, players=2, entities=4, max_sessions=8,
        hb_interval_ms=250, checkpoint_every=24, tick_interval_ms=20.0,
        warmup=True,
    )
    procs = [_spawn_agent(i, **spawn_kw) for i in range(2)]
    try:
        deadline = time.monotonic() + 240
        while len(director.hosts) < 2:
            director.step()
            time.sleep(0.005)
            assert time.monotonic() < deadline, "agents never registered"

        specs = [
            MatchSpec(match_id=m, players=2, ticks=2800, entities=4,
                      seed=300 + m)
            for m in range(2)
        ]
        for s in specs:
            director.place_match(s)

        # let the matches sync and build some confirmed history
        t_end = time.monotonic() + 8
        while time.monotonic() < t_end:
            director.step()
            time.sleep(0.005)
        pre = {}
        for rep in director.collect_reports(digests=False).values():
            for mid, entry in rep["islands"].items():
                pre[mid] = entry["histories"]
        assert any(h for hist in pre.values() for h in hist.values()), (
            "no confirmed history before the upgrade — the continuity "
            "check would be vacuous"
        )
        old_hosts = sorted(
            hid for hid, hr in director.hosts.items() if hr.alive()
        )

        ups = director.rolling_upgrade(
            lambda old_hid: procs.append(
                _spawn_agent(len(procs), **spawn_kw)
            ),
            register_timeout_ms=240_000,
        )
        assert len(ups) == 2
        assert sum(u["exported"] for u in ups) == 2  # every match moved
        # both originals exited the DRAIN path: clean 0, never fenced
        for i in (0, 1):
            assert procs[i].wait(timeout=30) == 0

        # the matches finish on the replacements
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            director.step()
            done = []
            for hid in (u["new_host"] for u in ups):
                hr = director.hosts[hid]
                done += [
                    e.get("done", False) for e in hr.islands.values()
                ]
            if done and all(done):
                break
            time.sleep(0.005)
        else:
            raise AssertionError("matches never finished post-upgrade")

        reports = director.collect_reports(digests=False)
        merged = {}
        for rep in reports.values():
            merged.update(rep["islands"])
        assert sorted(merged) == ["0", "1"]  # zero sessions/matches lost
        for mid, entry in merged.items():
            assert entry["desyncs"] == 0
            # zero confirmed frames lost, two witnesses (the history is
            # a bounded ring — MAX_CHECKSUM_HISTORY_SIZE — so ancient
            # pre-upgrade entries rotate out on a long match): every
            # pre-upgrade entry still retained is byte-identical, and
            # the retained window is gap-free at the desync-interval
            # stride — an upgrade that dropped confirmed frames would
            # tear a hole or fork the values (the in-process twin of
            # this test pins FULL continuity on an unpruned match)
            for peer, hist in pre.get(mid, {}).items():
                post = entry["histories"][peer]
                for f, c in hist.items():
                    if f in post:
                        assert post[f] == c, (mid, peer, f)
                frames = sorted(int(f) for f in post)
                gaps = {
                    frames[i + 1] - frames[i]
                    for i in range(len(frames) - 1)
                }
                assert gaps <= {10}, (mid, peer, gaps)
                assert frames and frames[-1] >= 2700  # ran to the end
        for hid in old_hosts:
            assert director.hosts[hid].state == "drained"
        director.shutdown_fleet()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass
