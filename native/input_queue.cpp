// Native per-player input queue: 128-slot ring with repeat-last-input
// prediction and misprediction detection — the C++ twin of
// ggrs_tpu/input_queue.py (which is the behavioral oracle; semantics follow
// the reference's src/input_queue.rs). Exposed via a C ABI handle API;
// ggrs_tpu/native/input_queue.py wraps it with the same Python interface so
// the sync layer can swap implementations.
//
// Error handling: operations that the Python twin treats as assertion
// failures return negative codes instead of aborting, so the binding can
// raise.

#include <cstdint>
#include <cstring>
#include <new>

#include "ggrs_native.h"

namespace {

constexpr int QUEUE_LEN = 128;
constexpr int NULL_FRAME = -1;
constexpr int MAX_INPUT_SIZE = 64;

constexpr long ERR_SEQUENCE = -2;   // inputs not added sequentially
constexpr long ERR_BAD_FRAME = -3;  // frame outside queue constraints
constexpr long ERR_PREDICTING = -4; // fetch while misprediction pending
constexpr long ERR_NOT_CONFIRMED = -5;
constexpr long ERR_OVERFLOW = -6;

struct Slot {
  int32_t frame;
  uint8_t buf[MAX_INPUT_SIZE];
};

struct Queue {
  int input_size;
  int head;
  int tail;
  int length;
  bool first_frame;
  int32_t last_added_frame;
  int32_t first_incorrect_frame;
  int32_t last_requested_frame;
  int frame_delay;
  Slot inputs[QUEUE_LEN];
  Slot prediction;
};

inline bool buf_equal(const Slot& a, const uint8_t* b, int n) {
  return std::memcmp(a.buf, b, n) == 0;
}

long add_input_by_frame(Queue* q, const uint8_t* buf, int32_t frame_number) {
  int prev = (q->head - 1 + QUEUE_LEN) % QUEUE_LEN;
  if (!(q->last_added_frame == NULL_FRAME ||
        frame_number == q->last_added_frame + 1))
    return ERR_SEQUENCE;
  if (!(frame_number == 0 || q->inputs[prev].frame == frame_number - 1))
    return ERR_BAD_FRAME;

  q->inputs[q->head].frame = frame_number;
  std::memcpy(q->inputs[q->head].buf, buf, q->input_size);
  q->head = (q->head + 1) % QUEUE_LEN;
  q->length += 1;
  if (q->length > QUEUE_LEN) return ERR_OVERFLOW;
  q->first_frame = false;
  q->last_added_frame = frame_number;

  if (q->prediction.frame != NULL_FRAME) {
    if (frame_number != q->prediction.frame) return ERR_BAD_FRAME;
    if (q->first_incorrect_frame == NULL_FRAME &&
        !buf_equal(q->prediction, buf, q->input_size)) {
      q->first_incorrect_frame = frame_number;
    }
    if (q->prediction.frame == q->last_requested_frame &&
        q->first_incorrect_frame == NULL_FRAME) {
      q->prediction.frame = NULL_FRAME;
    } else {
      q->prediction.frame += 1;
    }
  }
  return 0;
}

}  // namespace

extern "C" {

void* ggrs_iq_new(int input_size) {
  if (input_size < 1 || input_size > MAX_INPUT_SIZE) return nullptr;
  Queue* q = new (std::nothrow) Queue();
  if (!q) return nullptr;
  q->input_size = input_size;
  q->head = q->tail = q->length = 0;
  q->first_frame = true;
  q->last_added_frame = NULL_FRAME;
  q->first_incorrect_frame = NULL_FRAME;
  q->last_requested_frame = NULL_FRAME;
  q->frame_delay = 0;
  for (auto& s : q->inputs) {
    s.frame = NULL_FRAME;
    std::memset(s.buf, 0, MAX_INPUT_SIZE);
  }
  q->prediction.frame = NULL_FRAME;
  std::memset(q->prediction.buf, 0, MAX_INPUT_SIZE);
  return q;
}

void ggrs_iq_free(void* h) { delete static_cast<Queue*>(h); }

void ggrs_iq_set_frame_delay(void* h, int delay) {
  static_cast<Queue*>(h)->frame_delay = delay;
}

int32_t ggrs_iq_first_incorrect_frame(void* h) {
  return static_cast<Queue*>(h)->first_incorrect_frame;
}

int32_t ggrs_iq_last_added_frame(void* h) {
  return static_cast<Queue*>(h)->last_added_frame;
}

int ggrs_iq_length(void* h) { return static_cast<Queue*>(h)->length; }

void ggrs_iq_reset_prediction(void* h) {
  Queue* q = static_cast<Queue*>(h);
  q->prediction.frame = NULL_FRAME;
  q->first_incorrect_frame = NULL_FRAME;
  q->last_requested_frame = NULL_FRAME;
}

// Fetch confirmed input for a frame into out; 0 on success.
long ggrs_iq_confirmed_input(void* h, int32_t frame, uint8_t* out) {
  Queue* q = static_cast<Queue*>(h);
  int offset = ((frame % QUEUE_LEN) + QUEUE_LEN) % QUEUE_LEN;
  if (q->inputs[offset].frame != frame) return ERR_NOT_CONFIRMED;
  std::memcpy(out, q->inputs[offset].buf, q->input_size);
  return 0;
}

void ggrs_iq_discard_confirmed_frames(void* h, int32_t frame) {
  Queue* q = static_cast<Queue*>(h);
  if (q->last_requested_frame != NULL_FRAME && q->last_requested_frame < frame)
    frame = q->last_requested_frame;
  if (frame >= q->last_added_frame) {
    q->tail = q->head;
    q->length = 1;
  } else if (frame <= q->inputs[q->tail].frame) {
    // nothing to delete
  } else {
    int offset = frame - q->inputs[q->tail].frame;
    q->tail = (q->tail + offset) % QUEUE_LEN;
    q->length -= offset;
  }
}

// Input (confirmed or predicted) for a frame. Writes input_size bytes to
// out; returns 0 = confirmed, 1 = predicted, negative = error.
long ggrs_iq_input(void* h, int32_t requested_frame, uint8_t* out) {
  Queue* q = static_cast<Queue*>(h);
  if (q->first_incorrect_frame != NULL_FRAME) return ERR_PREDICTING;
  q->last_requested_frame = requested_frame;
  if (requested_frame < q->inputs[q->tail].frame) return ERR_BAD_FRAME;

  if (q->prediction.frame < 0) {
    int offset = requested_frame - q->inputs[q->tail].frame;
    if (offset < q->length) {
      int pos = (offset + q->tail) % QUEUE_LEN;
      if (q->inputs[pos].frame != requested_frame) return ERR_BAD_FRAME;
      std::memcpy(out, q->inputs[pos].buf, q->input_size);
      return 0;
    }
    if (requested_frame == 0 || q->last_added_frame == NULL_FRAME) {
      std::memset(q->prediction.buf, 0, q->input_size);
    } else {
      int prev = (q->head - 1 + QUEUE_LEN) % QUEUE_LEN;
      std::memcpy(q->prediction.buf, q->inputs[prev].buf, q->input_size);
      q->prediction.frame = q->inputs[prev].frame;
    }
    q->prediction.frame += 1;
  }
  if (q->prediction.frame == NULL_FRAME) return ERR_BAD_FRAME;
  std::memcpy(out, q->prediction.buf, q->input_size);
  return 1;
}

// Add the next sequential input; returns the frame it landed on after frame
// delay, NULL_FRAME (-1) if dropped, or a negative error < -1.
long ggrs_iq_add_input(void* h, int32_t frame, const uint8_t* buf) {
  Queue* q = static_cast<Queue*>(h);
  if (!(q->last_added_frame == NULL_FRAME ||
        frame + q->frame_delay == q->last_added_frame + 1))
    return ERR_SEQUENCE;

  // advance_queue_head (input_queue.rs:207-239)
  int prev = (q->head - 1 + QUEUE_LEN) % QUEUE_LEN;
  int32_t expected_frame = q->first_frame ? 0 : q->inputs[prev].frame + 1;
  int32_t input_frame = frame + q->frame_delay;
  if (expected_frame > input_frame) return NULL_FRAME;  // delay shrank: drop
  while (expected_frame < input_frame) {
    // delay grew: replicate the previous input to fill the gap
    long rc = add_input_by_frame(q, q->inputs[prev].buf, expected_frame);
    if (rc < 0) return rc;
    expected_frame += 1;
    prev = (q->head - 1 + QUEUE_LEN) % QUEUE_LEN;
  }
  long rc = add_input_by_frame(q, buf, input_frame);
  if (rc < 0) return rc;
  return input_frame;
}

}  // extern "C"
