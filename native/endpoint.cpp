// Native per-peer reliability endpoint: the C++ twin of
// ggrs_tpu/network/protocol.py (which mirrors the reference's UdpProtocol,
// src/network/protocol.rs:127-743). Wire format is byte-identical to
// ggrs_tpu/network/messages.py; compression reuses the delta+RLE kernels in
// ggrs_native.cpp. The Python wrapper (ggrs_tpu/native/endpoint.py) supplies
// wall-clock timestamps on every call, so injectable/fake clocks keep
// working and the state machine itself stays deterministic.
//
// Sessions interact through a small C ABI: queue-drain calls for outgoing
// wire packets and protocol events, byte-in for incoming packets.

#include <algorithm>
#include <array>
#include <cassert>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <set>
#include <vector>

#include "ggrs_native.h"

namespace {

constexpr int32_t NULL_FRAME = -1;
constexpr int UDP_HEADER_SIZE = 28;
constexpr int NUM_SYNC_PACKETS = 5;
constexpr uint64_t UDP_SHUTDOWN_TIMER_MS = 5000;
constexpr size_t PENDING_OUTPUT_SIZE = 128;
constexpr uint64_t SYNC_RETRY_INTERVAL_MS = 200;
constexpr uint64_t RUNNING_RETRY_INTERVAL_MS = 200;
constexpr uint64_t KEEP_ALIVE_INTERVAL_MS = 200;
constexpr uint64_t QUALITY_REPORT_INTERVAL_MS = 200;
constexpr size_t MAX_PAYLOAD = 467;
constexpr size_t MAX_CHECKSUM_HISTORY_SIZE = 32;
constexpr int FRAME_WINDOW_SIZE = 30;
constexpr int MAX_HANDLES = 16;
constexpr int MAX_INPUT_SIZE = 64;
// largest start_frame whose frame arithmetic cannot overflow int32
constexpr int32_t INT32_MAX_SAFE =
    0x7FFFFFFF - 2 * static_cast<int32_t>(PENDING_OUTPUT_SIZE);

// message body type tags (ggrs_tpu/network/messages.py:22-29)
// wire-layout sizes, named so the WIRE parity lint can pin them against
// messages.py's twins (WIRE_HEADER_SIZE etc): the Python batched pump
// (network/pump.py) gathers fields at these offsets out of pooled byte
// staging, so a drift here would silently desync the stacks
constexpr size_t WIRE_HEADER_SIZE = 3;          // magic u16 + body_type u8
constexpr size_t WIRE_INPUT_HEAD_SIZE = 10;     // start/ack i32 + flags + n
constexpr size_t WIRE_STATUS_SIZE = 5;          // disconnected u8 + frame i32
constexpr size_t WIRE_CHECKSUM_BODY_SIZE = 20;  // frame i32 + checksum u128

constexpr uint8_t MSG_SYNC_REQUEST = 0;
constexpr uint8_t MSG_SYNC_REPLY = 1;
constexpr uint8_t MSG_INPUT = 2;
constexpr uint8_t MSG_INPUT_ACK = 3;
constexpr uint8_t MSG_QUALITY_REPORT = 4;
constexpr uint8_t MSG_QUALITY_REPLY = 5;
constexpr uint8_t MSG_CHECKSUM_REPORT = 6;
constexpr uint8_t MSG_KEEP_ALIVE = 7;

enum class State : int32_t {
  kInitializing = 0,
  kSynchronizing = 1,
  kRunning = 2,
  kDisconnected = 3,
  kShutdown = 4,
};

// event type tags shared with the ctypes wrapper
constexpr int32_t EV_SYNCHRONIZING = 1;
constexpr int32_t EV_SYNCHRONIZED = 2;
constexpr int32_t EV_INPUT = 3;
constexpr int32_t EV_DISCONNECTED = 4;
constexpr int32_t EV_INTERRUPTED = 5;
constexpr int32_t EV_RESUMED = 6;

struct Event {
  int32_t type = 0;
  int32_t a = 0;  // Synchronizing: total; Interrupted: remaining timeout ms
  int32_t b = 0;  // Synchronizing: count
  int32_t frame = NULL_FRAME;
  int32_t player = 0;
  int32_t input_len = 0;
  uint8_t input[MAX_INPUT_SIZE] = {0};
};

struct ConnStatus {
  bool disconnected = false;
  int32_t last_frame = NULL_FRAME;
};

// ggrs_tpu/time_sync.py (reference src/time_sync.rs:3-39)
struct TimeSync {
  int32_t local[FRAME_WINDOW_SIZE] = {0};
  int32_t remote[FRAME_WINDOW_SIZE] = {0};

  void advance_frame(int32_t frame, int32_t local_adv, int32_t remote_adv) {
    int idx = ((frame % FRAME_WINDOW_SIZE) + FRAME_WINDOW_SIZE) % FRAME_WINDOW_SIZE;
    local[idx] = local_adv;
    remote[idx] = remote_adv;
  }

  int32_t average_frame_advantage() const {
    double local_sum = 0, remote_sum = 0;
    for (int i = 0; i < FRAME_WINDOW_SIZE; ++i) {
      local_sum += local[i];
      remote_sum += remote[i];
    }
    double local_avg = local_sum / FRAME_WINDOW_SIZE;
    double remote_avg = remote_sum / FRAME_WINDOW_SIZE;
    // truncation toward zero matches the reference's `as i32` cast
    return static_cast<int32_t>((remote_avg - local_avg) / 2.0);
  }
};

// little-endian scalar writers/readers
inline void put_u16(std::vector<uint8_t>& o, uint16_t v) {
  o.push_back(v & 0xFF);
  o.push_back(v >> 8);
}
inline void put_u32(std::vector<uint8_t>& o, uint32_t v) {
  for (int i = 0; i < 4; ++i) o.push_back((v >> (8 * i)) & 0xFF);
}
inline void put_u64(std::vector<uint8_t>& o, uint64_t v) {
  for (int i = 0; i < 8; ++i) o.push_back((v >> (8 * i)) & 0xFF);
}
inline void put_i32(std::vector<uint8_t>& o, int32_t v) {
  put_u32(o, static_cast<uint32_t>(v));
}

struct Reader {
  const uint8_t* p;
  long n;
  long off = 0;
  bool ok = true;

  uint8_t u8() {
    if (off + 1 > n) { ok = false; return 0; }
    return p[off++];
  }
  uint16_t u16() {
    if (off + 2 > n) { ok = false; return 0; }
    uint16_t v = p[off] | (p[off + 1] << 8);
    off += 2;
    return v;
  }
  uint32_t u32() {
    if (off + 4 > n) { ok = false; return 0; }
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) v |= static_cast<uint32_t>(p[off + i]) << (8 * i);
    off += 4;
    return v;
  }
  uint64_t u64() {
    if (off + 8 > n) { ok = false; return 0; }
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v |= static_cast<uint64_t>(p[off + i]) << (8 * i);
    off += 8;
    return v;
  }
  int32_t i32() { return static_cast<int32_t>(u32()); }
};

// xorshift64* nonce generator (seeded by the caller for determinism)
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ? seed : 0x9E3779B97F4A7C15ull) {}
  uint64_t next() {
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    return s * 0x2545F4914F6CDD1Dull;
  }
};

struct Endpoint {
  // config
  int32_t handles[MAX_HANDLES];
  long num_handles;
  long num_players;
  long local_players;
  long max_prediction;
  uint64_t disconnect_timeout_ms;
  uint64_t disconnect_notify_start_ms;
  long fps;
  long input_size;
  uint16_t magic;
  Rng rng;

  // state (field-for-field with PeerEndpoint.__init__)
  State state = State::kInitializing;
  int sync_remaining_roundtrips = NUM_SYNC_PACKETS;
  std::set<uint32_t> sync_random_requests;
  uint64_t running_last_quality_report;
  uint64_t running_last_input_recv;
  bool disconnect_notify_sent = false;
  bool disconnect_event_sent = false;
  uint64_t shutdown_timeout;
  uint16_t remote_magic = 0;
  std::vector<ConnStatus> peer_connect_status;

  std::deque<std::pair<int32_t, std::vector<uint8_t>>> pending_output;
  int32_t last_acked_frame = NULL_FRAME;
  std::vector<uint8_t> last_acked_bytes;
  std::map<int32_t, std::vector<uint8_t>> recv_inputs;

  TimeSync time_sync;
  int32_t local_frame_advantage = 0;
  int32_t remote_frame_advantage = 0;

  uint64_t stats_start_time = 0;
  uint64_t packets_sent = 0;
  uint64_t bytes_sent = 0;
  uint64_t round_trip_time = 0;
  uint64_t last_send_time;
  uint64_t last_recv_time;
  uint64_t last_sync_request_time;

  std::map<int32_t, std::array<uint8_t, 16>> checksum_history;
  int32_t last_added_checksum_frame = NULL_FRAME;

  std::deque<std::vector<uint8_t>> send_queue;
  std::deque<Event> event_queue;

  Endpoint(const int32_t* h, long nh, long np, long lp, long maxp,
           uint64_t dt, uint64_t dn, long fps_, long isz, uint16_t m,
           uint64_t seed, uint64_t now)
      : num_handles(nh),
        num_players(np),
        local_players(lp),
        max_prediction(maxp),
        disconnect_timeout_ms(dt),
        disconnect_notify_start_ms(dn),
        fps(fps_),
        input_size(isz),
        magic(m),
        rng(seed),
        running_last_quality_report(now),
        running_last_input_recv(now),
        shutdown_timeout(now),
        last_send_time(now),
        last_recv_time(now),
        last_sync_request_time(now) {
    std::copy(h, h + nh, handles);
    std::sort(handles, handles + nh);
    peer_connect_status.resize(np);
    last_acked_bytes.assign(isz * lp, 0);
    recv_inputs[NULL_FRAME] = std::vector<uint8_t>(isz * nh, 0);
  }

  int32_t last_recv_frame() const { return recv_inputs.rbegin()->first; }

  // ---- sending ------------------------------------------------------

  void queue_wire(std::vector<uint8_t> wire, uint64_t now) {
    packets_sent += 1;
    last_send_time = now;
    bytes_sent += wire.size();
    send_queue.push_back(std::move(wire));
  }

  std::vector<uint8_t> header(uint8_t body_type) const {
    std::vector<uint8_t> o;
    o.reserve(32);
    put_u16(o, magic);
    o.push_back(body_type);
    return o;
  }

  void send_sync_request(uint64_t now) {
    last_sync_request_time = now;
    uint32_t nonce = static_cast<uint32_t>(rng.next());
    sync_random_requests.insert(nonce);
    auto o = header(MSG_SYNC_REQUEST);
    put_u32(o, nonce);
    queue_wire(std::move(o), now);
  }

  void send_quality_report(uint64_t now) {
    running_last_quality_report = now;
    int32_t adv = std::max<int32_t>(-128, std::min<int32_t>(127, local_frame_advantage));
    auto o = header(MSG_QUALITY_REPORT);
    o.push_back(static_cast<uint8_t>(static_cast<int8_t>(adv)));
    put_u64(o, now);
    queue_wire(std::move(o), now);
  }

  void send_keep_alive(uint64_t now) {
    queue_wire(header(MSG_KEEP_ALIVE), now);
  }

  void send_input_ack(uint64_t now) {
    auto o = header(MSG_INPUT_ACK);
    put_i32(o, last_recv_frame());
    queue_wire(std::move(o), now);
  }

  void send_checksum_report(int32_t frame, const uint8_t csum[16], uint64_t now) {
    auto o = header(MSG_CHECKSUM_REPORT);
    put_i32(o, frame);
    o.insert(o.end(), csum, csum + 16);
    queue_wire(std::move(o), now);
  }

  void send_pending_output(const ConnStatus* status, long n_status, uint64_t now) {
    // (protocol.py _send_pending_output; reference protocol.rs:468-493)
    if (pending_output.empty()) return;
    int32_t first_frame = pending_output.front().first;
    assert(last_acked_frame == NULL_FRAME || last_acked_frame + 1 == first_frame);

    size_t count = pending_output.size();
    std::vector<uint8_t> payload = encode_window(count);
    while (payload.size() > MAX_PAYLOAD && count > 1) {
      count = std::max<size_t>(1, count / 2);
      payload = encode_window(count);
    }

    auto o = header(MSG_INPUT);
    put_i32(o, first_frame);
    put_i32(o, last_recv_frame());
    o.push_back(state == State::kDisconnected ? 1 : 0);
    o.push_back(static_cast<uint8_t>(n_status));
    for (long i = 0; i < n_status; ++i) {
      o.push_back(status[i].disconnected ? 1 : 0);
      put_i32(o, status[i].last_frame);
    }
    assert(payload.size() <= 0xFFFF);
    put_u16(o, static_cast<uint16_t>(payload.size()));
    o.insert(o.end(), payload.begin(), payload.end());
    queue_wire(std::move(o), now);
  }

  std::vector<uint8_t> encode_window(size_t count) {
    // delta vs last acked input, then RLE (compression.py encode)
    const long m = static_cast<long>(last_acked_bytes.size());
    std::vector<uint8_t> blob(m * count);
    size_t i = 0;
    for (auto it = pending_output.begin(); i < count; ++it, ++i) {
      assert(static_cast<long>(it->second.size()) == m);
      std::memcpy(blob.data() + i * m, it->second.data(), m);
    }
    std::vector<uint8_t> delta(std::max<size_t>(1, blob.size()));
    ggrs_delta_encode(last_acked_bytes.data(), m, blob.data(),
                      static_cast<long>(count), delta.data());
    std::vector<uint8_t> out(blob.size() + 32);
    long len = ggrs_rle_encode(delta.data(), static_cast<long>(blob.size()),
                               out.data(), static_cast<long>(out.size()));
    assert(len >= 0);
    out.resize(len);
    return out;
  }

  void send_input(int32_t frame, const uint8_t* data, long len,
                  const ConnStatus* status, long n_status, uint64_t now) {
    // (protocol.py send_input; reference protocol.rs:439-466)
    if (state != State::kRunning) return;
    time_sync.advance_frame(frame, local_frame_advantage, remote_frame_advantage);
    pending_output.emplace_back(frame, std::vector<uint8_t>(data, data + len));
    if (pending_output.size() > PENDING_OUTPUT_SIZE) {
      Event ev;
      ev.type = EV_DISCONNECTED;
      event_queue.push_back(ev);
    }
    send_pending_output(status, n_status, now);
  }

  // ---- timers -------------------------------------------------------

  void poll(const ConnStatus* status, long n_status, uint64_t now) {
    // (protocol.py poll; reference protocol.rs:351-404)
    if (state == State::kSynchronizing) {
      // retries key off the last sync REQUEST: QualityReplies to a running
      // peer would otherwise refresh last_send_time every 200ms and starve
      // the handshake forever (see protocol.py poll for the full story)
      if (last_sync_request_time + SYNC_RETRY_INTERVAL_MS < now)
        send_sync_request(now);
    } else if (state == State::kRunning) {
      if (running_last_input_recv + RUNNING_RETRY_INTERVAL_MS < now) {
        send_pending_output(status, n_status, now);
        running_last_input_recv = now;
      }
      if (running_last_quality_report + QUALITY_REPORT_INTERVAL_MS < now) {
        send_quality_report(now);
      }
      if (last_send_time + KEEP_ALIVE_INTERVAL_MS < now) send_keep_alive(now);
      if (!disconnect_notify_sent &&
          last_recv_time + disconnect_notify_start_ms < now) {
        Event ev;
        ev.type = EV_INTERRUPTED;
        ev.a = static_cast<int32_t>(disconnect_timeout_ms - disconnect_notify_start_ms);
        event_queue.push_back(ev);
        disconnect_notify_sent = true;
      }
      if (!disconnect_event_sent && last_recv_time + disconnect_timeout_ms < now) {
        Event ev;
        ev.type = EV_DISCONNECTED;
        event_queue.push_back(ev);
        disconnect_event_sent = true;
      }
    } else if (state == State::kDisconnected) {
      if (shutdown_timeout < now) state = State::kShutdown;
    }
  }

  // ---- receiving ----------------------------------------------------

  long handle_message(const uint8_t* buf, long n, uint64_t now) {
    // (protocol.py handle_message; reference protocol.rs:544-575)
    if (state == State::kShutdown) return 0;
    if (n < static_cast<long>(WIRE_HEADER_SIZE)) return -1;
    static_assert(WIRE_INPUT_HEAD_SIZE == 2 * sizeof(int32_t) + 2 &&
                      WIRE_STATUS_SIZE == 1 + sizeof(int32_t) &&
                      WIRE_CHECKSUM_BODY_SIZE == sizeof(int32_t) + 16,
                  "wire layout constants drifted from the field reads below");
    Reader r{buf, n};
    uint16_t msg_magic = r.u16();
    uint8_t body_type = r.u8();
    if (!r.ok) return -1;
    if (remote_magic != 0 && msg_magic != remote_magic) return 0;
    last_recv_time = now;
    if (disconnect_notify_sent && state == State::kRunning) {
      disconnect_notify_sent = false;
      Event ev;
      ev.type = EV_RESUMED;
      event_queue.push_back(ev);
    }

    switch (body_type) {
      case MSG_SYNC_REQUEST: {
        uint32_t nonce = r.u32();
        if (!r.ok) return -1;
        auto o = header(MSG_SYNC_REPLY);
        put_u32(o, nonce);
        queue_wire(std::move(o), now);
        return 0;
      }
      case MSG_SYNC_REPLY:
        return on_sync_reply(msg_magic, r, now);
      case MSG_INPUT:
        return on_input(r, now);
      case MSG_INPUT_ACK: {
        int32_t ack = r.i32();
        if (!r.ok) return -1;
        pop_pending_output(ack);
        return 0;
      }
      case MSG_QUALITY_REPORT: {
        int8_t adv = static_cast<int8_t>(r.u8());
        uint64_t ping = r.u64();
        if (!r.ok) return -1;
        remote_frame_advantage = adv;
        auto o = header(MSG_QUALITY_REPLY);
        put_u64(o, ping);
        queue_wire(std::move(o), now);
        return 0;
      }
      case MSG_QUALITY_REPLY: {
        uint64_t pong = r.u64();
        if (!r.ok) return -1;
        // network-controlled value: a pong from the future (clock skew or a
        // crafted packet) must not wrap the RTT or crash the process
        round_trip_time = now >= pong ? now - pong : 0;
        return 0;
      }
      case MSG_CHECKSUM_REPORT: {
        int32_t frame = r.i32();
        std::array<uint8_t, 16> csum;
        for (int i = 0; i < 16; ++i) csum[i] = r.u8();
        if (!r.ok) return -1;
        on_checksum_report(frame, csum);
        return 0;
      }
      case MSG_KEEP_ALIVE:
        return 0;  // nothing beyond the recv-time update
      default:
        return -1;
    }
  }

  long on_sync_reply(uint16_t msg_magic, Reader& r, uint64_t now) {
    uint32_t nonce = r.u32();
    if (!r.ok) return -1;
    if (state != State::kSynchronizing) return 0;
    if (!sync_random_requests.count(nonce)) return 0;
    sync_random_requests.erase(nonce);
    sync_remaining_roundtrips -= 1;
    if (sync_remaining_roundtrips > 0) {
      Event ev;
      ev.type = EV_SYNCHRONIZING;
      ev.a = NUM_SYNC_PACKETS;
      ev.b = NUM_SYNC_PACKETS - sync_remaining_roundtrips;
      event_queue.push_back(ev);
      send_sync_request(now);
    } else {
      state = State::kRunning;
      Event ev;
      ev.type = EV_SYNCHRONIZED;
      event_queue.push_back(ev);
      remote_magic = msg_magic;  // peer is now authorized
    }
    return 0;
  }

  long on_input(Reader& r, uint64_t now) {
    // (protocol.py _on_input; reference protocol.rs:616-689)
    int32_t start_frame = r.i32();
    int32_t ack_frame = r.i32();
    uint8_t flags = r.u8();
    uint8_t n_status = r.u8();
    if (!r.ok) return -1;
    std::vector<ConnStatus> statuses(n_status);
    for (int i = 0; i < n_status; ++i) {
      statuses[i].disconnected = r.u8() != 0;
      statuses[i].last_frame = r.i32();
    }
    uint16_t blen = r.u16();
    if (!r.ok || r.off + blen > r.n) return -1;
    const uint8_t* payload = r.p + r.off;

    pop_pending_output(ack_frame);

    if (flags & 1) {  // disconnect_requested
      if (state != State::kDisconnected && !disconnect_event_sent) {
        Event ev;
        ev.type = EV_DISCONNECTED;
        event_queue.push_back(ev);
        disconnect_event_sent = true;
      }
    } else {
      for (size_t i = 0; i < statuses.size() && i < peer_connect_status.size(); ++i) {
        auto& mine = peer_connect_status[i];
        mine.disconnected = statuses[i].disconnected || mine.disconnected;
        mine.last_frame = std::max(mine.last_frame, statuses[i].last_frame);
      }
    }

    int32_t last_recv = last_recv_frame();
    // a start_frame beyond last_recv+1 means the peer encoded against an
    // input we never received — unrecoverable for this packet, but it must
    // not abort the process (the value is network-controlled)
    if (last_recv != NULL_FRAME && start_frame > last_recv + 1) return -1;
    // before any input arrived, a legitimate first packet starts within the
    // sender's pending window; a huge spoofed start_frame would otherwise
    // poison recv_inputs and blackhole all real inputs
    if (last_recv == NULL_FRAME &&
        (start_frame < 0 ||
         start_frame > static_cast<int32_t>(PENDING_OUTPUT_SIZE)))
      return -1;
    // ...and the frame arithmetic below must never overflow int32 (UB in
    // either direction: start_frame - 1 at INT32_MIN, start_frame + k at
    // the top)
    if (start_frame < 0 || start_frame > INT32_MAX_SAFE) return -1;

    int32_t decode_frame = last_recv == NULL_FRAME ? NULL_FRAME : start_frame - 1;
    auto ref_it = recv_inputs.find(decode_frame);
    if (ref_it == recv_inputs.end()) return 0;
    running_last_input_recv = now;

    const std::vector<uint8_t>& ref = ref_it->second;
    const long m = static_cast<long>(ref.size());
    // decompression-bomb guard, same bound as the Python endpoint
    // (protocol.py _on_input): a legitimate sender never has more than
    // PENDING_OUTPUT_SIZE un-acked frames in flight
    std::vector<uint8_t> decoded(std::max<long>(m, 1) * (PENDING_OUTPUT_SIZE + 1));
    long dlen = ggrs_rle_decode(payload, blen, decoded.data(),
                                static_cast<long>(decoded.size()));
    if (dlen < 0 || m == 0 || dlen % m != 0) return -1;
    long k = dlen / m;
    std::vector<uint8_t> plain(std::max<long>(dlen, 1));
    ggrs_delta_encode(ref.data(), m, decoded.data(), k, plain.data());

    const long per_player = input_size;
    for (long i = 0; i < k; ++i) {
      int32_t inp_frame = start_frame + static_cast<int32_t>(i);
      if (inp_frame <= last_recv_frame()) continue;  // already have it
      const uint8_t* frame_bytes = plain.data() + i * m;
      recv_inputs[inp_frame].assign(frame_bytes, frame_bytes + m);
      assert(m == per_player * num_handles);
      for (long j = 0; j < num_handles; ++j) {
        Event ev;
        ev.type = EV_INPUT;
        ev.frame = inp_frame;
        ev.player = handles[j];
        ev.input_len = static_cast<int32_t>(per_player);
        std::memcpy(ev.input, frame_bytes + j * per_player, per_player);
        event_queue.push_back(ev);
      }
    }

    send_input_ack(now);

    // GC received inputs beyond 2x the prediction window
    int32_t horizon = last_recv_frame() - 2 * static_cast<int32_t>(max_prediction);
    for (auto it = recv_inputs.begin(); it != recv_inputs.end();) {
      if (it->first < horizon && it->first != NULL_FRAME) {
        it = recv_inputs.erase(it);
      } else {
        ++it;
      }
    }
    return 0;
  }

  void pop_pending_output(int32_t ack_frame) {
    while (!pending_output.empty() && pending_output.front().first <= ack_frame) {
      last_acked_frame = pending_output.front().first;
      last_acked_bytes = std::move(pending_output.front().second);
      pending_output.pop_front();
    }
  }

  void on_checksum_report(int32_t frame, const std::array<uint8_t, 16>& csum) {
    // (protocol.py _on_checksum_report; reference protocol.rs:711-722)
    if (last_added_checksum_frame < frame) {
      if (checksum_history.size() > MAX_CHECKSUM_HISTORY_SIZE) {
        int32_t keep_after = last_added_checksum_frame -
                             static_cast<int32_t>(MAX_CHECKSUM_HISTORY_SIZE);
        for (auto it = checksum_history.begin(); it != checksum_history.end();) {
          if (it->first <= keep_after) {
            it = checksum_history.erase(it);
          } else {
            ++it;
          }
        }
      }
      last_added_checksum_frame = frame;
      checksum_history[frame] = csum;
    }
  }

  // ---- stats --------------------------------------------------------

  void update_local_frame_advantage(int32_t local_frame) {
    // (protocol.py; reference protocol.rs:268-277)
    if (local_frame == NULL_FRAME || last_recv_frame() == NULL_FRAME) return;
    uint64_t ping = round_trip_time / 2;
    int32_t remote_frame =
        last_recv_frame() + static_cast<int32_t>((ping * fps) / 1000);
    local_frame_advantage = remote_frame - local_frame;
  }
};

}  // namespace

// struct layouts (ggrs_ep_config/_event/_stats) live in ggrs_native.h; the
// local tuning constants must stay in lockstep with its fixed array sizes
static_assert(MAX_HANDLES == 16, "ggrs_native.h pins handles[16]");
static_assert(MAX_INPUT_SIZE == 64, "ggrs_native.h pins input[64]");

extern "C" {

void* ggrs_ep_new(const ggrs_ep_config* cfg, uint64_t now_ms) {
  if (cfg->num_handles < 1 || cfg->num_handles > MAX_HANDLES) return nullptr;
  if (cfg->input_size < 1 || cfg->input_size > MAX_INPUT_SIZE) return nullptr;
  return new Endpoint(cfg->handles, cfg->num_handles, cfg->num_players,
                      cfg->local_players, cfg->max_prediction,
                      cfg->disconnect_timeout_ms, cfg->disconnect_notify_start_ms,
                      cfg->fps, cfg->input_size, cfg->magic, cfg->rng_seed,
                      now_ms);
}

void ggrs_ep_free(void* ep) { delete static_cast<Endpoint*>(ep); }

long ggrs_ep_state(void* ep) {
  return static_cast<long>(static_cast<Endpoint*>(ep)->state);
}

void ggrs_ep_synchronize(void* ep, uint64_t now_ms) {
  auto* e = static_cast<Endpoint*>(ep);
  assert(e->state == State::kInitializing);
  e->state = State::kSynchronizing;
  e->sync_remaining_roundtrips = NUM_SYNC_PACKETS;
  e->stats_start_time = now_ms;
  e->send_sync_request(now_ms);
}

void ggrs_ep_disconnect(void* ep, uint64_t now_ms) {
  auto* e = static_cast<Endpoint*>(ep);
  if (e->state == State::kShutdown) return;
  e->state = State::kDisconnected;
  e->shutdown_timeout = now_ms + UDP_SHUTDOWN_TIMER_MS;
}

void ggrs_ep_poll(void* ep, const uint8_t* disc, const int32_t* last, long n,
                  uint64_t now_ms) {
  std::vector<ConnStatus> status(n);
  for (long i = 0; i < n; ++i) {
    status[i].disconnected = disc[i] != 0;
    status[i].last_frame = last[i];
  }
  static_cast<Endpoint*>(ep)->poll(status.data(), n, now_ms);
}

void ggrs_ep_send_input(void* ep, int32_t frame, const uint8_t* data, long len,
                        const uint8_t* disc, const int32_t* last, long n,
                        uint64_t now_ms) {
  std::vector<ConnStatus> status(n);
  for (long i = 0; i < n; ++i) {
    status[i].disconnected = disc[i] != 0;
    status[i].last_frame = last[i];
  }
  static_cast<Endpoint*>(ep)->send_input(frame, data, len, status.data(), n,
                                         now_ms);
}

void ggrs_ep_send_checksum_report(void* ep, int32_t frame,
                                  const uint8_t* csum16, uint64_t now_ms) {
  static_cast<Endpoint*>(ep)->send_checksum_report(frame, csum16, now_ms);
}

long ggrs_ep_handle_message(void* ep, const uint8_t* buf, long len,
                            uint64_t now_ms) {
  return static_cast<Endpoint*>(ep)->handle_message(buf, len, now_ms);
}

void ggrs_ep_update_local_frame_advantage(void* ep, int32_t local_frame) {
  static_cast<Endpoint*>(ep)->update_local_frame_advantage(local_frame);
}

long ggrs_ep_average_frame_advantage(void* ep) {
  return static_cast<Endpoint*>(ep)->time_sync.average_frame_advantage();
}

// Drain one outgoing wire packet; returns its length, 0 when the queue is
// empty, or -1 if `cap` is too small. A SHUTDOWN endpoint drops its queue.
long ggrs_ep_next_send(void* ep, uint8_t* out, long cap) {
  auto* e = static_cast<Endpoint*>(ep);
  if (e->state == State::kShutdown) {
    e->send_queue.clear();
    return 0;
  }
  if (e->send_queue.empty()) return 0;
  const auto& wire = e->send_queue.front();
  if (static_cast<long>(wire.size()) > cap) return -1;
  std::memcpy(out, wire.data(), wire.size());
  long n = static_cast<long>(wire.size());
  e->send_queue.pop_front();
  return n;
}

long ggrs_ep_next_event(void* ep, ggrs_ep_event* out) {
  auto* e = static_cast<Endpoint*>(ep);
  if (e->event_queue.empty()) return 0;
  const Event& ev = e->event_queue.front();
  out->type = ev.type;
  out->a = ev.a;
  out->b = ev.b;
  out->frame = ev.frame;
  out->player = ev.player;
  out->input_len = ev.input_len;
  std::memcpy(out->input, ev.input, MAX_INPUT_SIZE);
  e->event_queue.pop_front();
  return 1;
}

long ggrs_ep_network_stats(void* ep, uint64_t now_ms, ggrs_ep_stats* out) {
  auto* e = static_cast<Endpoint*>(ep);
  if (e->state != State::kSynchronizing && e->state != State::kRunning) return -1;
  uint64_t seconds = (now_ms - e->stats_start_time) / 1000;
  if (seconds == 0) return -1;
  uint64_t total_bytes = e->bytes_sent + e->packets_sent * UDP_HEADER_SIZE;
  out->send_queue_len = static_cast<int32_t>(e->pending_output.size());
  out->ping_ms = static_cast<uint32_t>(e->round_trip_time);
  out->kbps_sent = static_cast<uint32_t>((total_bytes / seconds) / 1024);
  out->local_frames_behind = e->local_frame_advantage;
  out->remote_frames_behind = e->remote_frame_advantage;
  return 0;
}

void ggrs_ep_peer_connect_status(void* ep, uint8_t* disc, int32_t* last, long n) {
  auto* e = static_cast<Endpoint*>(ep);
  for (long i = 0; i < n && i < static_cast<long>(e->peer_connect_status.size());
       ++i) {
    disc[i] = e->peer_connect_status[i].disconnected ? 1 : 0;
    last[i] = e->peer_connect_status[i].last_frame;
  }
}

// Copy up to `cap` (frame, u128 checksum) entries; returns the count.
long ggrs_ep_checksum_history(void* ep, int32_t* frames, uint8_t* sums16,
                              long cap) {
  auto* e = static_cast<Endpoint*>(ep);
  long i = 0;
  for (const auto& [frame, csum] : e->checksum_history) {
    if (i >= cap) break;
    frames[i] = frame;
    std::memcpy(sums16 + i * 16, csum.data(), 16);
    ++i;
  }
  return i;
}

}  // extern "C"
