// Public C ABI of the ggrs_tpu native runtime (the C1 "crate root" analog;
// reference src/lib.rs:45-279). Everything here is exported from
// libggrs_native.so with C linkage and plain-old-data arguments, so the
// runtime is consumable from C/C++ directly as well as via the ctypes
// bindings in ggrs_tpu/native/.
//
// Families:
//   ggrs_rle_* / ggrs_delta_* / ggrs_weighted_checksum  codec kernels
//       (ggrs_native.cpp; format oracle: ggrs_tpu/network/compression.py)
//   ggrs_iq_*    per-player input queue (input_queue.cpp; oracle:
//                ggrs_tpu/input_queue.py; reference src/input_queue.rs)
//   ggrs_ep_*    per-peer reliability endpoint incl. TimeSync + stats
//                (endpoint.cpp; oracle: ggrs_tpu/network/protocol.py;
//                reference src/network/protocol.rs)
//   ggrs_udp_*   nonblocking UDP socket (udp_socket.cpp; reference
//                src/network/udp_socket.rs)
//   ggrs_sess_*  session core: SyncLayer + P2P / SyncTest / Spectator
//                state machines (session.cpp; oracles: ggrs_tpu/sessions/;
//                reference src/sessions/, src/sync_layer.rs)
//
// Conventions:
//   * handles are opaque void*; every ggrs_X_new has a ggrs_X_free
//   * all clock-dependent calls take now_ms (caller-supplied monotonic
//     milliseconds) — the library never reads a clock, so hosts can drive
//     deterministic fake time
//   * functions return 0/length on success; negative codes are errors
//     (see the GGRS_SERR_* values below for the session family)
//   * frames are int32 with -1 = NULL_FRAME (reference src/lib.rs:46)
//
// ggrs_native_abi_version() must match the consumer's expectation (the
// ctypes loader pins it); bump it whenever this surface changes.
//
// THREADING CONTRACT (the reference's `sync-send` analog,
// src/lib.rs:203-237 — there, sessions are Send but not Sync; here the
// same rules stated for a C ABI):
//   * Every handle (ggrs_iq_*, ggrs_ep_*, ggrs_udp_*, ggrs_sess_*) is
//     UNSYNCHRONIZED mutable state: no internal locking, no atomics.
//     Concurrent calls into the SAME handle from two threads are a data
//     race and undefined behavior.
//   * Handles are not thread-AFFINE: any thread may call into a handle
//     provided calls are externally serialized (a mutex, a channel, or a
//     migration handoff with a happens-before edge — the C equivalent of
//     Rust's Send). Creating on one thread and using on another is fine.
//   * DIFFERENT handles are fully independent: two threads each driving
//     their own session/endpoint/queue never contend — the library has no
//     shared mutable globals (verified: the only globals are const
//     tables; tests/test_native_session.py drives two sessions from two
//     threads concurrently as the regression gate).
//   * ggrs_X_free must not race any call on the same handle, including
//     another free (same rule as above: frees are calls).
//   * Stateless codec kernels (ggrs_rle_*, ggrs_delta_*,
//     ggrs_weighted_checksum, ggrs_siphash24) touch only their arguments
//     and are safe to call from any number of threads concurrently on
//     disjoint buffers.
// The Python layer adds its own serialization (the GIL) on top; the
// contract above is what a C/C++ embedder must uphold.

#ifndef GGRS_NATIVE_H_
#define GGRS_NATIVE_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

// ---------------------------------------------------------------------------
// versioning
// ---------------------------------------------------------------------------

long ggrs_native_abi_version(void);

// ---------------------------------------------------------------------------
// codec kernels (XOR-delta + byte RLE input compression, state checksum)
// ---------------------------------------------------------------------------

long ggrs_rle_encode(const uint8_t* in, long n, uint8_t* out, long cap);
long ggrs_rle_decode(const uint8_t* in, long n, uint8_t* out, long cap);
void ggrs_delta_encode(const uint8_t* ref, long m, const uint8_t* inputs,
                       long k, uint8_t* out);
void ggrs_delta_decode(const uint8_t* ref, long m, const uint8_t* data,
                       long k, uint8_t* out);
void ggrs_weighted_checksum(const uint32_t* words, long n, uint32_t* hi,
                            uint32_t* lo);
// SipHash-2-4 MAC tag (authenticated transport; 128-bit key, 64-bit tag)
void ggrs_siphash24(const uint8_t key[16], const uint8_t* data, long n,
                    uint8_t out[8]);

// ---------------------------------------------------------------------------
// input queue (128-slot ring, repeat-last prediction, misprediction detect)
// ---------------------------------------------------------------------------

void* ggrs_iq_new(int input_size);  // input_size in [1, 64]
void ggrs_iq_free(void* q);
void ggrs_iq_set_frame_delay(void* q, int delay);
int32_t ggrs_iq_first_incorrect_frame(void* q);
int32_t ggrs_iq_last_added_frame(void* q);
int ggrs_iq_length(void* q);
void ggrs_iq_reset_prediction(void* q);
long ggrs_iq_confirmed_input(void* q, int32_t frame, uint8_t* out);
void ggrs_iq_discard_confirmed_frames(void* q, int32_t frame);
long ggrs_iq_input(void* q, int32_t frame, uint8_t* out);  // 0 confirmed, 1 predicted
long ggrs_iq_add_input(void* q, int32_t frame, const uint8_t* buf);

// ---------------------------------------------------------------------------
// reliability endpoint (sync handshake, delta+RLE input send/ack, timers,
// disconnect detection, RTT/quality, checksum reports, TimeSync)
// ---------------------------------------------------------------------------

struct ggrs_ep_config {
  int32_t handles[16];
  long num_handles;
  long num_players;
  long local_players;
  long max_prediction;
  long disconnect_timeout_ms;
  long disconnect_notify_start_ms;
  long fps;
  long input_size;
  uint16_t magic;
  uint64_t rng_seed;
};

// event types: 1 Synchronizing(a=total,b=count), 2 Synchronized,
// 3 Input(frame,player,input), 4 Disconnected, 5 Interrupted(a=timeout_ms),
// 6 Resumed
struct ggrs_ep_event {
  int32_t type;
  int32_t a;
  int32_t b;
  int32_t frame;
  int32_t player;
  int32_t input_len;
  uint8_t input[64];
};

struct ggrs_ep_stats {
  int32_t send_queue_len;
  uint32_t ping_ms;
  uint32_t kbps_sent;
  int32_t local_frames_behind;
  int32_t remote_frames_behind;
};

void* ggrs_ep_new(const struct ggrs_ep_config* cfg, uint64_t now_ms);
void ggrs_ep_free(void* ep);
long ggrs_ep_state(void* ep);  // 0 init, 1 syncing, 2 running, 3 disc, 4 shutdown
void ggrs_ep_synchronize(void* ep, uint64_t now_ms);
void ggrs_ep_disconnect(void* ep, uint64_t now_ms);
void ggrs_ep_poll(void* ep, const uint8_t* disc, const int32_t* last, long n,
                  uint64_t now_ms);
void ggrs_ep_send_input(void* ep, int32_t frame, const uint8_t* data, long len,
                        const uint8_t* disc, const int32_t* last, long n,
                        uint64_t now_ms);
void ggrs_ep_send_checksum_report(void* ep, int32_t frame,
                                  const uint8_t* csum16, uint64_t now_ms);
long ggrs_ep_handle_message(void* ep, const uint8_t* buf, long len,
                            uint64_t now_ms);
void ggrs_ep_update_local_frame_advantage(void* ep, int32_t local_frame);
long ggrs_ep_average_frame_advantage(void* ep);
long ggrs_ep_next_send(void* ep, uint8_t* out, long cap);
long ggrs_ep_next_event(void* ep, struct ggrs_ep_event* out);
long ggrs_ep_network_stats(void* ep, uint64_t now_ms, struct ggrs_ep_stats* out);
void ggrs_ep_peer_connect_status(void* ep, uint8_t* disc, int32_t* last, long n);
long ggrs_ep_checksum_history(void* ep, int32_t* frames, uint8_t* sums16,
                              long cap);

// ---------------------------------------------------------------------------
// UDP socket (fd-based; addresses are host-byte-order IPv4 + port)
// ---------------------------------------------------------------------------

long ggrs_udp_bind(long port);  // nonblocking 0.0.0.0:port; fd or -1
long ggrs_udp_local_port(long fd);
void ggrs_udp_close(long fd);
long ggrs_udp_send(long fd, const uint8_t* buf, long len, uint32_t ip_host,
                   uint16_t port);
// length, -1 = drained (EWOULDBLOCK), -2 = transient error (skip)
long ggrs_udp_recv(long fd, uint8_t* buf, long cap, uint32_t* ip_host,
                   uint16_t* port);

// ---------------------------------------------------------------------------
// session core (SyncLayer + P2P / SyncTest / Spectator)
// ---------------------------------------------------------------------------

#define GGRS_SESS_P2P 0
#define GGRS_SESS_SYNCTEST 1
#define GGRS_SESS_SPECTATOR 2

#define GGRS_KIND_LOCAL 0
#define GGRS_KIND_REMOTE 1
#define GGRS_KIND_SPECTATOR 2

// session error codes
#define GGRS_SERR_NOT_SYNCHRONIZED (-2)
#define GGRS_SERR_PREDICTION_THRESHOLD (-3)
#define GGRS_SERR_MISSING_INPUT (-4)
#define GGRS_SERR_MISMATCHED_CHECKSUM (-5)
#define GGRS_SERR_SPECTATOR_TOO_FAR_BEHIND (-6)
#define GGRS_SERR_INVALID_HANDLE (-7)
#define GGRS_SERR_LOCAL_PLAYER (-8)
#define GGRS_SERR_ALREADY_DISCONNECTED (-9)
#define GGRS_SERR_INTERNAL (-10)
#define GGRS_SERR_CAPACITY (-11)

struct ggrs_sess_config {
  int32_t session_type;  // GGRS_SESS_*
  int32_t num_players;
  int32_t max_prediction;
  int32_t input_size;
  int32_t input_delay;
  int32_t sparse_saving;
  int32_t desync_interval;  // 0 = off
  int32_t check_distance;
  int32_t max_frames_behind;
  int32_t catchup_speed;
  int32_t fps;
  int32_t disconnect_timeout_ms;
  int32_t disconnect_notify_start_ms;
  int32_t total_handles;                // players + spectators
  int32_t num_endpoints;                // unique remote addresses
  int32_t player_kinds[32];             // GGRS_KIND_* per handle, -1 = unused
  int32_t player_endpoints[32];         // endpoint index per handle, -1 local
  uint64_t rng_seed;
};

// ordered requests (the reference's GGRSRequest contract, src/lib.rs:169-194):
// type 0 = SaveGameState (cell = snapshot ring slot), 1 = LoadGameState,
// 2 = AdvanceFrame (statuses: 0 confirmed, 1 predicted, 2 disconnected;
// inputs packed per player)
struct ggrs_sess_req {
  int32_t type;
  int32_t frame;
  int32_t cell;
  int32_t statuses[16];
  uint8_t inputs[16 * 64];
};

// session events: 1 Synchronizing(ep,a=total,b=count), 2 Synchronized(ep),
// 3 Disconnected(ep), 4 NetworkInterrupted(ep,a=timeout_ms),
// 5 NetworkResumed(ep), 6 WaitRecommendation(a=skip_frames),
// 7 DesyncDetected(ep,a=frame,local/remote checksums)
struct ggrs_sess_event {
  int32_t type;
  int32_t ep;
  int32_t a;
  int32_t b;
  uint8_t local_checksum[16];
  uint8_t remote_checksum[16];
};

void* ggrs_sess_new(const struct ggrs_sess_config* cfg, uint64_t now_ms);
void ggrs_sess_free(void* s);
long ggrs_sess_state(void* s);  // 0 synchronizing, 1 running
int32_t ggrs_sess_current_frame(void* s);
int32_t ggrs_sess_confirmed_frame(void* s);
int32_t ggrs_sess_last_saved_frame(void* s);
long ggrs_sess_frames_ahead(void* s);
int32_t ggrs_sess_frames_behind_host(void* s);  // spectator sessions
int32_t ggrs_sess_last_error_frame(void* s);    // MismatchedChecksum detail
void ggrs_sess_connect_status(void* s, uint8_t* disc, int32_t* last, long n);
// wire I/O: the host routes datagrams between addresses and endpoint indices
void ggrs_sess_handle_wire(void* s, long ep, const uint8_t* buf, long len,
                           uint64_t now_ms);
long ggrs_sess_drain_wire(void* s, int32_t* ep_out, uint8_t* buf, long cap);
void ggrs_sess_poll(void* s, uint64_t now_ms);
long ggrs_sess_add_local_input(void* s, long handle, const uint8_t* buf);
long ggrs_sess_advance_frame(void* s, uint64_t now_ms,
                             struct ggrs_sess_req* out, long cap);
int32_t ggrs_sess_request_count(void* s);
long ggrs_sess_copy_requests(void* s, struct ggrs_sess_req* out, long cap);
long ggrs_sess_next_event(void* s, struct ggrs_sess_event* out);
long ggrs_sess_disconnect_player(void* s, long handle, uint64_t now_ms);
long ggrs_sess_network_stats(void* s, long ep, uint64_t now_ms,
                             struct ggrs_ep_stats* out);
// desync detection: the host materializes the snapshot checksum the core
// requests, then feeds it back (report + local history natively)
int32_t ggrs_sess_take_checksum_request(void* s);
void ggrs_sess_provide_checksum(void* s, int32_t frame, const uint8_t* csum16,
                                uint64_t now_ms);
// SyncTest verification: compare-or-record an observed (frame, checksum)
// against the first-seen history; prunes entries older than oldest_allowed
long ggrs_sess_st_verify(void* s, int32_t frame, int has,
                         const uint8_t* csum16, int32_t oldest_allowed);

#ifdef __cplusplus
}  // extern "C"
#endif

#endif  // GGRS_NATIVE_H_
