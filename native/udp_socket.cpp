// Nonblocking UDP transport: the C++ twin of UdpNonBlockingSocket
// (ggrs_tpu/network/sockets.py; reference src/network/udp_socket.rs:17-55).
// Plain POSIX sockets behind a C ABI; the Python wrapper drains datagrams
// in a loop until EWOULDBLOCK, mirroring the reference's recv loop.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include "ggrs_native.h"

extern "C" {

// Bind 0.0.0.0:port nonblocking; returns the fd or -1.
long ggrs_udp_bind(long port) {
  int fd = ::socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return -1;
  int flags = ::fcntl(fd, F_GETFL, 0);
  if (flags < 0 || ::fcntl(fd, F_SETFL, flags | O_NONBLOCK) < 0) {
    ::close(fd);
    return -1;
  }
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

long ggrs_udp_local_port(long fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (::getsockname(static_cast<int>(fd), reinterpret_cast<sockaddr*>(&addr),
                    &len) < 0) {
    return -1;
  }
  return ntohs(addr.sin_port);
}

void ggrs_udp_close(long fd) { ::close(static_cast<int>(fd)); }

// Send one datagram to ipv4 (host byte order) : port. Returns bytes sent
// or -1 on error (nonblocking sends on UDP effectively never block).
long ggrs_udp_send(long fd, const uint8_t* buf, long len, uint32_t ip_host,
                   uint16_t port) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(ip_host);
  addr.sin_port = htons(port);
  long n = ::sendto(static_cast<int>(fd), buf, len, 0,
                    reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
  return n;
}

// Receive one datagram. Returns its length, -1 when the queue is drained
// (EWOULDBLOCK), or -2 on a transient error the caller should skip
// (e.g. ECONNRESET from a peer's ICMP port-unreachable).
long ggrs_udp_recv(long fd, uint8_t* buf, long cap, uint32_t* ip_host,
                   uint16_t* port) {
  sockaddr_in src{};
  socklen_t slen = sizeof(src);
  long n = ::recvfrom(static_cast<int>(fd), buf, cap, 0,
                      reinterpret_cast<sockaddr*>(&src), &slen);
  if (n < 0) {
    if (errno == EWOULDBLOCK || errno == EAGAIN) return -1;
    return -2;
  }
  *ip_host = ntohl(src.sin_addr.s_addr);
  *port = ntohs(src.sin_port);
  return n;
}

}  // extern "C"
