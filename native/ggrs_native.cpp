// Native hot-path kernels for the ggrs_tpu host runtime.
//
// The reference implements its whole runtime natively (Rust); here the
// per-packet codec hot path — XOR-delta + byte RLE input compression
// (format-identical to ggrs_tpu/network/compression.py, which is the
// oracle) — and the host-side snapshot checksum are C++, exposed through a
// plain C ABI consumed via ctypes (ggrs_tpu/native/__init__.py).
//
// Every function is allocation-free: callers pass output buffers; functions
// return the produced length or a negative error code.

#include <cstdint>
#include <cstring>

#include "ggrs_native.h"

namespace {

constexpr int TOKEN_LITERAL = 0;
constexpr int TOKEN_ZEROS = 1;
constexpr int TOKEN_ONES = 2;
constexpr long MIN_RUN = 3;           // runs shorter than this stay literal
constexpr long MAX_CHUNK = 1L << 20;  // literal chunk cap (matches Python)

// LEB128 varint append; returns new offset or -1 on overflow.
inline long write_varint(uint8_t* out, long cap, long off, uint64_t v) {
  while (true) {
    if (off >= cap) return -1;
    uint8_t b = v & 0x7F;
    v >>= 7;
    if (v) {
      out[off++] = b | 0x80;
    } else {
      out[off++] = b;
      return off;
    }
  }
}

// LEB128 varint read; returns new offset or -1 on truncation/overflow.
inline long read_varint(const uint8_t* in, long n, long off, uint64_t* v) {
  int shift = 0;
  uint64_t acc = 0;
  while (true) {
    if (off >= n) return -1;
    uint8_t b = in[off++];
    acc |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *v = acc;
      return off;
    }
    shift += 7;
    if (shift > 35) return -1;
  }
}

inline long flush_literal(const uint8_t* data, long lit_start, long end,
                          uint8_t* out, long cap, long off) {
  while (lit_start < end) {
    long chunk = end - lit_start;
    if (chunk > MAX_CHUNK) chunk = MAX_CHUNK;
    off = write_varint(out, cap, off,
                       (static_cast<uint64_t>(chunk) << 2) | TOKEN_LITERAL);
    if (off < 0 || off + chunk > cap) return -1;
    std::memcpy(out + off, data + lit_start, chunk);
    off += chunk;
    lit_start += chunk;
  }
  return off;
}

}  // namespace

extern "C" {

// RLE encode `n` bytes of `in` into `out` (capacity `cap`).
// Returns encoded length, or -1 if out is too small.
long ggrs_rle_encode(const uint8_t* in, long n, uint8_t* out, long cap) {
  long off = 0;
  long i = 0;
  long lit_start = 0;
  while (i < n) {
    uint8_t b = in[i];
    if (b == 0x00 || b == 0xFF) {
      long j = i + 1;
      while (j < n && in[j] == b) ++j;
      long run = j - i;
      if (run >= MIN_RUN) {
        off = flush_literal(in, lit_start, i, out, cap, off);
        if (off < 0) return -1;
        int token = (b == 0x00) ? TOKEN_ZEROS : TOKEN_ONES;
        off = write_varint(out, cap, off,
                           (static_cast<uint64_t>(run) << 2) | token);
        if (off < 0) return -1;
        i = j;
        lit_start = i;
        continue;
      }
      i = j;
    } else {
      ++i;
    }
  }
  off = flush_literal(in, lit_start, n, out, cap, off);
  return off;
}

// RLE decode; returns decoded length, -1 on malformed input, -2 if out too small.
long ggrs_rle_decode(const uint8_t* in, long n, uint8_t* out, long cap) {
  long off = 0;
  long w = 0;
  while (off < n) {
    uint64_t v;
    off = read_varint(in, n, off, &v);
    if (off < 0) return -1;
    int kind = static_cast<int>(v & 3);
    long length = static_cast<long>(v >> 2);
    if (w + length > cap) return -2;
    if (kind == TOKEN_LITERAL) {
      if (off + length > n) return -1;
      std::memcpy(out + w, in + off, length);
      off += length;
    } else if (kind == TOKEN_ZEROS) {
      std::memset(out + w, 0x00, length);
    } else if (kind == TOKEN_ONES) {
      std::memset(out + w, 0xFF, length);
    } else {
      return -1;
    }
    w += length;
  }
  return w;
}

// XOR each of `k` consecutive inputs (each `m` bytes, concatenated in
// `inputs`) against `ref` (m bytes) into `out` (k*m bytes).
void ggrs_delta_encode(const uint8_t* ref, long m, const uint8_t* inputs,
                       long k, uint8_t* out) {
  for (long c = 0; c < k; ++c) {
    const uint8_t* src = inputs + c * m;
    uint8_t* dst = out + c * m;
    for (long i = 0; i < m; ++i) dst[i] = src[i] ^ ref[i];
  }
}

// Inverse of ggrs_delta_encode (XOR is an involution).
void ggrs_delta_decode(const uint8_t* ref, long m, const uint8_t* data,
                       long k, uint8_t* out) {
  ggrs_delta_encode(ref, m, data, k, out);
}

// Order-invariant 64-bit checksum of a uint32 word vector; bit-identical to
// ggrs_tpu.ops.fixed_point.weighted_checksum (Knuth-weighted modular sums).
void ggrs_weighted_checksum(const uint32_t* words, long n, uint32_t* hi,
                            uint32_t* lo) {
  const uint32_t GOLDEN = 2654435761u;
  uint32_t h = 0, l = 0;
  for (long i = 0; i < n; ++i) {
    uint32_t w = words[i];
    h += w * (static_cast<uint32_t>(i + 1) * GOLDEN);
    l += w;
  }
  *hi = h;
  *lo = l;
}

// SipHash-2-4: per-datagram MAC tag for the authenticated transport
// (ggrs_tpu/network/auth.py is the oracle; tags must match bit-for-bit).
void ggrs_siphash24(const uint8_t key[16], const uint8_t* data, long n,
                    uint8_t out[8]) {
  auto rotl = [](uint64_t x, int b) { return (x << b) | (x >> (64 - b)); };
  auto load64 = [](const uint8_t* p) {
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v |= static_cast<uint64_t>(p[i]) << (8 * i);
    return v;
  };
  uint64_t k0 = load64(key), k1 = load64(key + 8);
  uint64_t v0 = 0x736f6d6570736575ull ^ k0;
  uint64_t v1 = 0x646f72616e646f6dull ^ k1;
  uint64_t v2 = 0x6c7967656e657261ull ^ k0;
  uint64_t v3 = 0x7465646279746573ull ^ k1;
  auto round = [&] {
    v0 += v1; v1 = rotl(v1, 13); v1 ^= v0; v0 = rotl(v0, 32);
    v2 += v3; v3 = rotl(v3, 16); v3 ^= v2;
    v0 += v3; v3 = rotl(v3, 21); v3 ^= v0;
    v2 += v1; v1 = rotl(v1, 17); v1 ^= v2; v2 = rotl(v2, 32);
  };
  long full = n - (n % 8);
  for (long off = 0; off < full; off += 8) {
    uint64_t m = load64(data + off);
    v3 ^= m; round(); round(); v0 ^= m;
  }
  uint64_t last = static_cast<uint64_t>(n & 0xFF) << 56;
  for (long i = 0; i < n % 8; ++i)
    last |= static_cast<uint64_t>(data[full + i]) << (8 * i);
  v3 ^= last; round(); round(); v0 ^= last;
  v2 ^= 0xFF;
  round(); round(); round(); round();
  uint64_t tag = v0 ^ v1 ^ v2 ^ v3;
  for (int i = 0; i < 8; ++i) out[i] = (tag >> (8 * i)) & 0xFF;
}

// ABI version for the ctypes loader to sanity-check. Bump whenever exported
// symbols change (v2: added the ggrs_iq_* input-queue family; v3: the
// ggrs_ep_* reliability endpoint and ggrs_udp_* socket families; v4: the
// ggrs_sess_* session core family; v5: ggrs_siphash24).
long ggrs_native_abi_version() { return 5; }

}  // extern "C"
