// Native session core: the C++ twin of the session layer —
// SyncLayer (ggrs_tpu/sync_layer.py; reference src/sync_layer.rs),
// P2PSession (ggrs_tpu/sessions/p2p_session.py; reference
// src/sessions/p2p_session.rs), SyncTestSession
// (ggrs_tpu/sessions/sync_test_session.py; reference
// src/sessions/sync_test_session.rs) and SpectatorSession
// (ggrs_tpu/sessions/spectator_session.py; reference
// src/sessions/p2p_spectator_session.rs). The Python twins are the
// behavioral oracles; tests drive native and Python sessions in lockstep.
//
// Composition happens natively: the session owns C++ input queues
// (input_queue.cpp) and C++ reliability endpoints (endpoint.cpp) through
// their C ABI, so a full tick — message intake, rollback bookkeeping,
// input send — runs without touching Python. The boundaries that stay
// host-side, exposed through the C ABI below:
//   * wire I/O: the wrapper routes datagrams addr<->endpoint-index and owns
//     the socket (UDP or the fault-injecting in-memory net),
//   * game state: requests reference snapshot-ring cell indices; the
//     wrapper owns the GameStateCells (user objects or device ring slots),
//   * checksums: opaque to the core; the wrapper materializes them
//     (possibly lazily off-device) and feeds them back for desync
//     detection / SyncTest verification,
//   * clocks: every stateful call takes now_ms, preserving the injectable
//     fake-clock determinism seam.
//
// Error handling: operations the Python twins treat as exceptions return
// negative codes (GGRS_SERR_*) so the binding can raise the same types.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <new>
#include <vector>

#include "ggrs_native.h"  // sibling-TU ABI + this TU's exported structs

namespace {

constexpr int32_t NULL_FRAME = -1;
constexpr int32_t INT32_MAX_FRAME = 0x7FFFFFFF;
constexpr int MAX_PLAYERS = 16;
constexpr int MAX_TOTAL_HANDLES = 32;
constexpr int MAX_EPS = 32;
constexpr int MAX_INPUT_SIZE = 64;
constexpr size_t MAX_EVENT_QUEUE = 100;  // builder.py MAX_EVENT_QUEUE_SIZE
constexpr int SPECTATOR_BUFFER = 60;     // builder.py SPECTATOR_BUFFER_SIZE
constexpr int RECOMMENDATION_INTERVAL = 60;  // p2p_session.py:54
constexpr int MIN_RECOMMENDATION = 3;        // p2p_session.py:55
constexpr size_t MAX_CHECKSUM_HISTORY = 32;  // protocol MAX_CHECKSUM_HISTORY_SIZE

// session types
constexpr int32_t SESS_P2P = 0;
constexpr int32_t SESS_SYNCTEST = 1;
constexpr int32_t SESS_SPECTATOR = 2;

// player kinds (types.py PlayerTypeKind)
constexpr int32_t KIND_LOCAL = 0;
constexpr int32_t KIND_REMOTE = 1;
constexpr int32_t KIND_SPECTATOR = 2;

// endpoint protocol states (endpoint.cpp State)
constexpr long EP_RUNNING = 2;
constexpr long EP_DISCONNECTED = 3;
constexpr long EP_SHUTDOWN = 4;

// endpoint event tags (endpoint.cpp EV_*)
constexpr int32_t EP_EV_SYNCHRONIZING = 1;
constexpr int32_t EP_EV_SYNCHRONIZED = 2;
constexpr int32_t EP_EV_INPUT = 3;
constexpr int32_t EP_EV_DISCONNECTED = 4;
constexpr int32_t EP_EV_INTERRUPTED = 5;
constexpr int32_t EP_EV_RESUMED = 6;

// session event tags (shared with ggrs_tpu/native/session.py)
constexpr int32_t SEV_SYNCHRONIZING = 1;
constexpr int32_t SEV_SYNCHRONIZED = 2;
constexpr int32_t SEV_DISCONNECTED = 3;
constexpr int32_t SEV_INTERRUPTED = 4;
constexpr int32_t SEV_RESUMED = 5;
constexpr int32_t SEV_WAIT_RECOMMENDATION = 6;
constexpr int32_t SEV_DESYNC_DETECTED = 7;

// request tags (types.py SaveGameState/LoadGameState/AdvanceFrame)
constexpr int32_t REQ_SAVE = 0;
constexpr int32_t REQ_LOAD = 1;
constexpr int32_t REQ_ADVANCE = 2;

// input statuses (types.py InputStatus)
constexpr int32_t STATUS_CONFIRMED = 0;
constexpr int32_t STATUS_PREDICTED = 1;
constexpr int32_t STATUS_DISCONNECTED = 2;

// error codes (errors.py via ggrs_tpu/native/session.py)
constexpr long SERR_NOT_SYNCHRONIZED = -2;
constexpr long SERR_PREDICTION_THRESHOLD = -3;
constexpr long SERR_MISSING_INPUT = -4;
constexpr long SERR_MISMATCHED_CHECKSUM = -5;
constexpr long SERR_SPECTATOR_TOO_FAR_BEHIND = -6;
constexpr long SERR_INVALID_HANDLE = -7;
constexpr long SERR_LOCAL_PLAYER = -8;
constexpr long SERR_ALREADY_DISCONNECTED = -9;
constexpr long SERR_INTERNAL = -10;
constexpr long SERR_CAPACITY = -11;

struct ConnStatus {
  bool disconnected = false;
  int32_t last_frame = NULL_FRAME;
};

struct Checksum {
  bool has = false;  // user may save without a checksum (None in Python)
  uint8_t bytes[16] = {0};

  bool operator==(const Checksum& o) const {
    return has == o.has && std::memcmp(bytes, o.bytes, 16) == 0;
  }
};

struct Req {
  int32_t type;
  int32_t frame;
  int32_t cell;
  int32_t statuses[MAX_PLAYERS];
  uint8_t inputs[MAX_PLAYERS * MAX_INPUT_SIZE];
};

struct SessEvent {
  int32_t type = 0;
  int32_t ep = -1;
  int32_t a = 0;
  int32_t b = 0;
  uint8_t local_checksum[16] = {0};
  uint8_t remote_checksum[16] = {0};
};

// xorshift64* (same generator as endpoint.cpp, independently seeded)
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ? seed : 0x9E3779B97F4A7C15ull) {}
  uint64_t next() {
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    return s * 0x2545F4914F6CDD1Dull;
  }
};

// The C4 twin: snapshot-ring bookkeeping + per-player queues
// (ggrs_tpu/sync_layer.py SyncLayer; reference src/sync_layer.rs:78-273).
// Cells hold only the frame stamp; snapshot data lives with the caller.
struct NativeSyncLayer {
  int num_players = 0;
  int max_prediction = 0;
  int input_size = 0;
  std::vector<int32_t> ring_frames;  // frame % (max_prediction + 2) addressing
  int32_t last_confirmed_frame = NULL_FRAME;
  int32_t last_saved_frame = NULL_FRAME;
  int32_t current_frame = 0;
  void* queues[MAX_PLAYERS] = {nullptr};

  bool init(int np, int maxp, int isz) {
    num_players = np;
    max_prediction = maxp;
    input_size = isz;
    ring_frames.assign(maxp + 2, NULL_FRAME);
    for (int i = 0; i < np; ++i) {
      queues[i] = ggrs_iq_new(isz);
      if (!queues[i]) return false;
    }
    return true;
  }

  ~NativeSyncLayer() {
    for (auto*& q : queues) {
      if (q) ggrs_iq_free(q);
      q = nullptr;
    }
  }

  int cell_of(int32_t frame) const {
    return static_cast<int>(frame % static_cast<int32_t>(ring_frames.size()));
  }

  void save_current_state(Req* r) {
    last_saved_frame = current_frame;
    int cell = cell_of(current_frame);
    ring_frames[cell] = current_frame;
    r->type = REQ_SAVE;
    r->frame = current_frame;
    r->cell = cell;
  }

  // (sync_layer.py load_frame; reference src/sync_layer.rs:139-155)
  long load_frame(int32_t frame_to_load, Req* r) {
    if (frame_to_load == NULL_FRAME || frame_to_load >= current_frame ||
        frame_to_load < current_frame - max_prediction)
      return SERR_INTERNAL;
    int cell = cell_of(frame_to_load);
    if (ring_frames[cell] != frame_to_load) return SERR_INTERNAL;
    current_frame = frame_to_load;
    r->type = REQ_LOAD;
    r->frame = frame_to_load;
    r->cell = cell;
    return 0;
  }

  // prediction-threshold gate + queue insert (sync_layer.py add_local_input;
  // reference src/sync_layer.rs:159-174). Returns the landed frame or error.
  long add_local_input(int handle, const uint8_t* buf) {
    int32_t frames_ahead = current_frame - last_confirmed_frame;
    if (current_frame >= max_prediction && frames_ahead >= max_prediction)
      return SERR_PREDICTION_THRESHOLD;
    long rc = ggrs_iq_add_input(queues[handle], current_frame, buf);
    if (rc < 0) return SERR_INTERNAL;  // dropped or sequence violation
    return rc;
  }

  void reset_prediction() {
    for (int i = 0; i < num_players; ++i) ggrs_iq_reset_prediction(queues[i]);
  }

  // (sync_layer.py synchronized_inputs; reference src/sync_layer.rs:187-200)
  long synchronized_inputs(const ConnStatus* status, Req* r) {
    r->type = REQ_ADVANCE;
    r->frame = current_frame;
    r->cell = -1;
    std::memset(r->inputs, 0, sizeof(r->inputs));
    for (int i = 0; i < num_players; ++i) {
      uint8_t* out = r->inputs + i * input_size;
      if (status[i].disconnected && status[i].last_frame < current_frame) {
        r->statuses[i] = STATUS_DISCONNECTED;  // zeroed dummy
      } else {
        long rc = ggrs_iq_input(queues[i], current_frame, out);
        if (rc < 0) return SERR_INTERNAL;
        r->statuses[i] = rc == 0 ? STATUS_CONFIRMED : STATUS_PREDICTED;
      }
    }
    return 0;
  }

  // (sync_layer.py confirmed_inputs; reference src/sync_layer.rs:203-217)
  long confirmed_inputs(int32_t frame, const ConnStatus* status, uint8_t* out) {
    for (int i = 0; i < num_players; ++i) {
      uint8_t* dst = out + i * input_size;
      if (status[i].disconnected && status[i].last_frame < frame) {
        std::memset(dst, 0, input_size);
      } else {
        long rc = ggrs_iq_confirmed_input(queues[i], frame, dst);
        if (rc < 0) return SERR_INTERNAL;
      }
    }
    return 0;
  }

  // (sync_layer.py set_last_confirmed_frame; reference src/sync_layer.rs:220-244)
  long set_last_confirmed_frame(int32_t frame, bool sparse_saving) {
    int32_t first_incorrect = NULL_FRAME;
    for (int i = 0; i < num_players; ++i)
      first_incorrect =
          std::max(first_incorrect, ggrs_iq_first_incorrect_frame(queues[i]));

    if (sparse_saving) frame = std::min(frame, last_saved_frame);

    if (!(first_incorrect == NULL_FRAME || first_incorrect >= frame))
      return SERR_INTERNAL;  // would discard inputs still needed for rollback
    last_confirmed_frame = frame;
    if (last_confirmed_frame > 0)
      for (int i = 0; i < num_players; ++i)
        ggrs_iq_discard_confirmed_frames(queues[i], frame - 1);
    return 0;
  }

  // (sync_layer.py check_simulation_consistency; reference src/sync_layer.rs:247-257)
  int32_t check_simulation_consistency(int32_t first_incorrect) const {
    for (int i = 0; i < num_players; ++i) {
      int32_t incorrect = ggrs_iq_first_incorrect_frame(queues[i]);
      if (incorrect != NULL_FRAME &&
          (first_incorrect == NULL_FRAME || incorrect < first_incorrect))
        first_incorrect = incorrect;
    }
    return first_incorrect;
  }

  bool has_saved_frame(int32_t frame) const {
    return frame >= 0 &&
           ring_frames[frame % static_cast<int32_t>(ring_frames.size())] == frame;
  }
};

struct EndpointSlot {
  void* ep = nullptr;
  std::vector<int32_t> handles;  // sorted player handles behind this address
  bool is_spectator = false;     // spectator endpoint of a P2P host
};

struct Session {
  // config
  int32_t type = SESS_P2P;
  int num_players = 0;
  int max_prediction = 0;
  int input_size = 0;
  bool sparse_saving = false;
  int desync_interval = 0;  // 0 = off
  int check_distance = 0;
  int max_frames_behind = 10;
  int catchup_speed = 1;
  int total_handles = 0;
  int32_t kinds[MAX_TOTAL_HANDLES];
  int32_t ep_of_handle[MAX_TOTAL_HANDLES];

  // shared state
  bool running = false;  // SessionState: false = SYNCHRONIZING
  NativeSyncLayer sync;
  std::vector<EndpointSlot> eps;
  std::deque<SessEvent> events;
  std::vector<Req> reqs;
  int32_t last_error_frame = NULL_FRAME;

  // p2p state (p2p_session.py __init__)
  ConnStatus local_connect_status[MAX_PLAYERS];
  int32_t disconnect_frame = NULL_FRAME;
  int32_t next_recommended_sleep = 0;
  int32_t next_spectator_frame = 0;
  int32_t frames_ahead = 0;
  bool staged_valid[MAX_PLAYERS] = {false};
  uint8_t staged_inputs[MAX_PLAYERS][MAX_INPUT_SIZE];
  int32_t pending_checksum_request = NULL_FRAME;
  std::map<int32_t, Checksum> local_checksum_history;

  // synctest state
  ConnStatus dummy_status[MAX_PLAYERS];
  std::map<int32_t, Checksum> st_history;

  // spectator state (spectator_session.py __init__)
  int32_t spec_current_frame = NULL_FRAME;
  int32_t spec_last_recv_frame = NULL_FRAME;
  struct SpecSlot {
    int32_t frame = NULL_FRAME;
    uint8_t buf[MAX_INPUT_SIZE] = {0};
  };
  std::vector<SpecSlot> spec_inputs;  // SPECTATOR_BUFFER * num_players
  ConnStatus host_connect_status[MAX_PLAYERS];

  // wire drain cursor
  size_t drain_ep = 0;

  void push_event(const SessEvent& ev) {
    events.push_back(ev);
    while (events.size() > MAX_EVENT_QUEUE) events.pop_front();
  }

  bool ep_synchronized(const EndpointSlot& slot) const {
    long s = ggrs_ep_state(slot.ep);
    return s == EP_RUNNING || s == EP_DISCONNECTED || s == EP_SHUTDOWN;
  }

  // (p2p_session.py _check_initial_sync)
  void check_initial_sync() {
    if (running) return;
    for (const auto& slot : eps)
      if (!ep_synchronized(slot)) return;
    running = true;
  }

  void pack_status(uint8_t* disc, int32_t* last) const {
    const ConnStatus* src =
        type == SESS_SPECTATOR ? host_connect_status : local_connect_status;
    for (int i = 0; i < num_players; ++i) {
      disc[i] = src[i].disconnected ? 1 : 0;
      last[i] = src[i].last_frame;
    }
  }

  // ---- P2P internals --------------------------------------------------

  // (p2p_session.py confirmed_frame; reference p2p_session.rs:487-498)
  int32_t confirmed_frame() const {
    int32_t confirmed = INT32_MAX_FRAME;
    for (int i = 0; i < num_players; ++i)
      if (!local_connect_status[i].disconnected)
        confirmed = std::min(confirmed, local_connect_status[i].last_frame);
    return confirmed;  // INT32_MAX_FRAME = every player disconnected
  }

  // (p2p_session.py _disconnect_player_at_frame; reference p2p_session.rs:555-595)
  void disconnect_player_at_frame(int handle, int32_t last_frame, uint64_t now) {
    int32_t kind = kinds[handle];
    int ep_idx = ep_of_handle[handle];
    if (kind == KIND_REMOTE && ep_idx >= 0) {
      EndpointSlot& slot = eps[ep_idx];
      for (int32_t h : slot.handles)
        if (h < num_players) local_connect_status[h].disconnected = true;
      ggrs_ep_disconnect(slot.ep, now);
      if (sync.current_frame > last_frame)
        // resimulate from the disconnect so predictions made for the dead
        // player are redone with Disconnected dummy inputs
        disconnect_frame = last_frame + 1;
    } else if (kind == KIND_SPECTATOR && ep_idx >= 0) {
      ggrs_ep_disconnect(eps[ep_idx].ep, now);
    }
    check_initial_sync();
  }

  // (p2p_session.py _update_player_disconnects; reference p2p_session.rs:707-742)
  void update_player_disconnects(uint64_t now) {
    // one status fetch per running endpoint, reused across all handles (the
    // statuses cannot change mid-loop — no packets are processed here); the
    // running check stays per-iteration because an earlier handle's
    // disconnect can stop an endpoint, and the Python twin re-evaluates it
    uint8_t disc[MAX_EPS][MAX_PLAYERS];
    int32_t last[MAX_EPS][MAX_PLAYERS];
    bool fetched[MAX_EPS];
    for (size_t e = 0; e < eps.size(); ++e) {
      fetched[e] = !eps[e].is_spectator && ggrs_ep_state(eps[e].ep) == EP_RUNNING;
      if (fetched[e])
        ggrs_ep_peer_connect_status(eps[e].ep, disc[e], last[e], num_players);
    }
    for (int handle = 0; handle < num_players; ++handle) {
      bool queue_connected = true;
      int32_t queue_min_confirmed = INT32_MAX_FRAME;
      for (size_t e = 0; e < eps.size(); ++e) {
        if (!fetched[e] || ggrs_ep_state(eps[e].ep) != EP_RUNNING) continue;
        queue_connected = queue_connected && !disc[e][handle];
        queue_min_confirmed = std::min(queue_min_confirmed, last[e][handle]);
      }

      bool local_connected = !local_connect_status[handle].disconnected;
      int32_t local_min_confirmed = local_connect_status[handle].last_frame;
      if (local_connected)
        queue_min_confirmed = std::min(queue_min_confirmed, local_min_confirmed);

      if (!queue_connected &&
          (local_connected || local_min_confirmed > queue_min_confirmed))
        disconnect_player_at_frame(handle, queue_min_confirmed, now);
    }
  }

  // (p2p_session.py _adjust_gamestate; reference p2p_session.rs:621-673)
  long adjust_gamestate(int32_t first_incorrect, int32_t min_confirmed) {
    int32_t current_frame = sync.current_frame;
    int32_t frame_to_load =
        sparse_saving ? sync.last_saved_frame : first_incorrect;
    if (frame_to_load > first_incorrect) return SERR_INTERNAL;
    int32_t count = current_frame - frame_to_load;

    reqs.emplace_back();
    long rc = sync.load_frame(frame_to_load, &reqs.back());
    if (rc < 0) return rc;
    sync.reset_prediction();

    for (int32_t i = 0; i < count; ++i) {
      Req advance;
      rc = sync.synchronized_inputs(
          type == SESS_SYNCTEST ? dummy_status : local_connect_status, &advance);
      if (rc < 0) return rc;
      if (type == SESS_P2P && sparse_saving) {
        if (sync.current_frame == min_confirmed) {
          reqs.emplace_back();
          sync.save_current_state(&reqs.back());
        }
      } else {
        if (i > 0) {
          reqs.emplace_back();
          sync.save_current_state(&reqs.back());
        }
      }
      sync.current_frame += 1;
      reqs.push_back(advance);
    }
    return sync.current_frame == current_frame ? 0 : SERR_INTERNAL;
  }

  // sparse-saving keepalive of the snapshot ring
  // (p2p_session.py _check_last_saved_state; reference p2p_session.rs:778-802)
  long check_last_saved_state(int32_t last_saved, int32_t confirmed) {
    if (sync.current_frame - last_saved >= max_prediction) {
      if (confirmed >= sync.current_frame) {
        reqs.emplace_back();
        sync.save_current_state(&reqs.back());
      } else {
        long rc = adjust_gamestate(last_saved, confirmed);
        if (rc < 0) return rc;
      }
    }
    return 0;
  }

  // (p2p_session.py _send_confirmed_inputs_to_spectators; reference
  // p2p_session.rs:676-703)
  long send_confirmed_inputs_to_spectators(int32_t confirmed, uint64_t now) {
    bool have_spectators = false;
    for (const auto& slot : eps) have_spectators |= slot.is_spectator;
    if (!have_spectators) return 0;

    uint8_t disc[MAX_PLAYERS];
    int32_t last[MAX_PLAYERS];
    uint8_t data[MAX_PLAYERS * MAX_INPUT_SIZE];
    while (next_spectator_frame <= confirmed) {
      long rc = sync.confirmed_inputs(next_spectator_frame, local_connect_status,
                                      data);
      if (rc < 0) return rc;
      pack_status(disc, last);
      for (auto& slot : eps) {
        if (!slot.is_spectator) continue;
        if (ggrs_ep_state(slot.ep) != EP_RUNNING) continue;
        ggrs_ep_send_input(slot.ep, next_spectator_frame, data,
                           num_players * input_size, disc, last, num_players,
                           now);
      }
      next_spectator_frame += 1;
    }
    return 0;
  }

  // (p2p_session.py _max_frame_advantage / _check_wait_recommendation)
  void check_wait_recommendation() {
    bool any = false;
    int32_t interval = 0;
    for (const auto& slot : eps) {
      if (slot.is_spectator) continue;
      for (int32_t h : slot.handles) {
        if (h < num_players && !local_connect_status[h].disconnected) {
          int32_t adv =
              static_cast<int32_t>(ggrs_ep_average_frame_advantage(slot.ep));
          interval = any ? std::max(interval, adv) : adv;
          any = true;
        }
      }
    }
    frames_ahead = any ? interval : 0;

    if (sync.current_frame > next_recommended_sleep &&
        frames_ahead >= MIN_RECOMMENDATION) {
      next_recommended_sleep = sync.current_frame + RECOMMENDATION_INTERVAL;
      SessEvent ev;
      ev.type = SEV_WAIT_RECOMMENDATION;
      ev.a = frames_ahead;
      push_event(ev);
    }
  }

  // desync detection (p2p_session.py _check_checksum_send_interval; the
  // materialization/flush policy lives in the Python wrapper, which answers
  // pending_checksum_request via ggrs_sess_provide_checksum)
  void check_checksum_send_interval(int32_t confirmed) {
    int32_t current = sync.current_frame;
    // only frames <= confirmed are bit-identical across peers (deliberate
    // divergence from the reference, see p2p_session.py:530-538)
    int32_t frame_to_send = std::min(sync.last_saved_frame - 1, confirmed);
    if (current % desync_interval == 0 && frame_to_send > max_prediction &&
        sync.has_saved_frame(frame_to_send))
      pending_checksum_request = frame_to_send;

    if (local_checksum_history.size() > MAX_CHECKSUM_HISTORY) {
      int32_t keep_after = current - static_cast<int32_t>(MAX_CHECKSUM_HISTORY);
      for (auto it = local_checksum_history.begin();
           it != local_checksum_history.end();) {
        if (it->first <= keep_after)
          it = local_checksum_history.erase(it);
        else
          ++it;
      }
    }
  }

  // (p2p_session.py _compare_local_checksums_against_peers)
  void compare_checksums_against_peers() {
    if (sync.current_frame % desync_interval != 0) return;
    int32_t frames[64];
    uint8_t sums[64 * 16];
    for (size_t e = 0; e < eps.size(); ++e) {
      if (eps[e].is_spectator) continue;
      long n = ggrs_ep_checksum_history(eps[e].ep, frames, sums, 64);
      for (long i = 0; i < n; ++i) {
        auto it = local_checksum_history.find(frames[i]);
        if (it == local_checksum_history.end() || !it->second.has) continue;
        if (std::memcmp(it->second.bytes, sums + i * 16, 16) != 0) {
          SessEvent ev;
          ev.type = SEV_DESYNC_DETECTED;
          ev.ep = static_cast<int32_t>(e);
          ev.a = frames[i];
          std::memcpy(ev.local_checksum, it->second.bytes, 16);
          std::memcpy(ev.remote_checksum, sums + i * 16, 16);
          push_event(ev);
        }
      }
    }
  }

  // (p2p_session.py _handle_event; reference p2p_session.rs:805-871)
  void handle_ep_event(const ggrs_ep_event& ev, size_t ep_idx, uint64_t now) {
    const EndpointSlot& slot = eps[ep_idx];
    SessEvent out;
    out.ep = static_cast<int32_t>(ep_idx);
    switch (ev.type) {
      case EP_EV_SYNCHRONIZING:
        out.type = SEV_SYNCHRONIZING;
        out.a = ev.a;
        out.b = ev.b;
        push_event(out);
        break;
      case EP_EV_SYNCHRONIZED:
        if (type == SESS_SPECTATOR)
          running = true;
        else
          check_initial_sync();
        out.type = SEV_SYNCHRONIZED;
        push_event(out);
        break;
      case EP_EV_INTERRUPTED:
        out.type = SEV_INTERRUPTED;
        out.a = ev.a;
        push_event(out);
        break;
      case EP_EV_RESUMED:
        out.type = SEV_RESUMED;
        push_event(out);
        break;
      case EP_EV_DISCONNECTED:
        if (type == SESS_P2P) {
          for (int32_t h : slot.handles) {
            int32_t last_frame = h < num_players
                                     ? local_connect_status[h].last_frame
                                     : NULL_FRAME;  // spectator
            disconnect_player_at_frame(h, last_frame, now);
          }
        }
        out.type = SEV_DISCONNECTED;
        push_event(out);
        break;
      case EP_EV_INPUT:
        if (type == SESS_P2P) {
          int32_t player = ev.player;
          if (player < 0 || player >= num_players) break;
          if (local_connect_status[player].disconnected) break;
          int32_t current_remote = local_connect_status[player].last_frame;
          // remote inputs must arrive in sequence; the endpoint guarantees
          // this, so a violation is a protocol bug — drop defensively where
          // the Python twin asserts
          if (!(current_remote == NULL_FRAME || current_remote + 1 == ev.frame))
            break;
          local_connect_status[player].last_frame = ev.frame;
          ggrs_iq_add_input(sync.queues[player], ev.frame, ev.input);
        } else if (type == SESS_SPECTATOR) {
          // (spectator_session.py _handle_event EvInput branch)
          // mirror the P2P branch's bounds guard: a buggy/changed endpoint
          // must not become an out-of-bounds write into spec_inputs
          if (ev.player < 0 || ev.player >= num_players || ev.frame < 0) break;
          if (ev.frame < spec_last_recv_frame) break;  // defensive
          SpecSlot& cell =
              spec_inputs[(ev.frame % SPECTATOR_BUFFER) * num_players +
                          ev.player];
          cell.frame = ev.frame;
          std::memcpy(cell.buf, ev.input, input_size);
          spec_last_recv_frame = ev.frame;
          ggrs_ep_update_local_frame_advantage(slot.ep, ev.frame);
          uint8_t disc[MAX_PLAYERS];
          int32_t last[MAX_PLAYERS];
          ggrs_ep_peer_connect_status(slot.ep, disc, last, num_players);
          for (int i = 0; i < num_players; ++i) {
            host_connect_status[i].disconnected = disc[i] != 0;
            host_connect_status[i].last_frame = last[i];
          }
        }
        break;
      default:
        break;
    }
  }

  // (p2p_session.py poll_remote_clients minus socket I/O, which the wrapper
  // does around this; reference p2p_session.rs:375-423)
  void poll(uint64_t now) {
    if (type != SESS_SPECTATOR) {
      for (const auto& slot : eps) {
        if (slot.is_spectator) continue;
        if (ggrs_ep_state(slot.ep) == EP_RUNNING)
          ggrs_ep_update_local_frame_advantage(slot.ep, sync.current_frame);
      }
    }

    uint8_t disc[MAX_PLAYERS];
    int32_t last[MAX_PLAYERS];
    pack_status(disc, last);

    // collect all events first, then handle — matches the Python twin's
    // two-phase loop so a disconnect triggered by one endpoint's event
    // doesn't change which events later endpoints emit this poll
    std::vector<std::pair<ggrs_ep_event, size_t>> collected;
    for (size_t e = 0; e < eps.size(); ++e) {
      ggrs_ep_poll(eps[e].ep, disc, last, num_players, now);
      ggrs_ep_event ev;
      while (ggrs_ep_next_event(eps[e].ep, &ev)) collected.emplace_back(ev, e);
    }
    for (const auto& [ev, e] : collected) handle_ep_event(ev, e, now);
  }

  // ---- per-session-type advance ---------------------------------------

  // (p2p_session.py advance_frame; reference p2p_session.rs:253-371)
  long advance_p2p(uint64_t now) {
    if (!running) return SERR_NOT_SYNCHRONIZED;
    reqs.clear();

    if (sync.current_frame == 0) {
      reqs.emplace_back();
      sync.save_current_state(&reqs.back());
    }

    update_player_disconnects(now);
    int32_t confirmed = confirmed_frame();
    if (confirmed == INT32_MAX_FRAME) return SERR_INTERNAL;

    int32_t first_incorrect = sync.check_simulation_consistency(disconnect_frame);
    if (first_incorrect != NULL_FRAME) {
      // a disconnect recorded at exactly the current frame needs no rollback
      // (see p2p_session.py:176-182)
      if (first_incorrect < sync.current_frame) {
        long rc = adjust_gamestate(first_incorrect, confirmed);
        if (rc < 0) return rc;
      }
      disconnect_frame = NULL_FRAME;
    }

    int32_t last_saved = sync.last_saved_frame;
    if (sparse_saving) {
      long rc = check_last_saved_state(last_saved, confirmed);
      if (rc < 0) return rc;
    } else {
      reqs.emplace_back();
      sync.save_current_state(&reqs.back());
    }

    // broadcast precedes GC with the same watermark, so GC can never discard
    // a frame the spectators haven't been sent
    long rc = send_confirmed_inputs_to_spectators(confirmed, now);
    if (rc < 0) return rc;
    rc = sync.set_last_confirmed_frame(confirmed, sparse_saving);
    if (rc < 0) return rc;

    if (desync_interval > 0) {
      check_checksum_send_interval(confirmed);
      compare_checksums_against_peers();
    }

    check_wait_recommendation();

    // register local inputs (stamped with the current frame at staging time)
    int32_t actual_frame = NULL_FRAME;
    uint8_t local_blob[MAX_PLAYERS * MAX_INPUT_SIZE];
    long local_len = 0;
    for (int h = 0; h < num_players; ++h) {
      if (kinds[h] != KIND_LOCAL) continue;
      if (!staged_valid[h]) return SERR_MISSING_INPUT;
      long landed = sync.add_local_input(h, staged_inputs[h]);
      if (landed < 0) return landed;
      if (landed == NULL_FRAME) return SERR_INTERNAL;
      actual_frame = static_cast<int32_t>(landed);  // input delay may shift it
      local_connect_status[h].last_frame = actual_frame;
      std::memcpy(local_blob + local_len, staged_inputs[h], input_size);
      local_len += input_size;
    }

    uint8_t disc[MAX_PLAYERS];
    int32_t last[MAX_PLAYERS];
    pack_status(disc, last);
    for (auto& slot : eps) {
      if (slot.is_spectator) continue;
      ggrs_ep_send_input(slot.ep, actual_frame, local_blob, local_len, disc,
                         last, num_players, now);
    }
    for (int h = 0; h < num_players; ++h) staged_valid[h] = false;

    // second spectator broadcast: the watermark recomputed after the local
    // inputs landed covers the current frame (see p2p_session.py:222-231)
    bool have_spectators = false;
    for (const auto& slot : eps) have_spectators |= slot.is_spectator;
    if (have_spectators) {
      rc = send_confirmed_inputs_to_spectators(confirmed_frame(), now);
      if (rc < 0) return rc;
    }

    Req advance;
    rc = sync.synchronized_inputs(local_connect_status, &advance);
    if (rc < 0) return rc;
    sync.current_frame += 1;
    reqs.push_back(advance);
    return static_cast<long>(reqs.size());
  }

  // (sync_test_session.py advance_frame minus the checksum comparisons,
  // which the wrapper drives via ggrs_sess_st_verify; reference
  // src/sessions/sync_test_session.rs:85-146)
  long advance_synctest() {
    reqs.clear();

    if (check_distance > 0 && sync.current_frame > check_distance) {
      long rc = adjust_gamestate_synctest(sync.current_frame - check_distance);
      if (rc < 0) return rc;
    }

    for (int h = 0; h < num_players; ++h)
      if (!staged_valid[h]) return SERR_MISSING_INPUT;
    for (int h = 0; h < num_players; ++h) {
      long landed = sync.add_local_input(h, staged_inputs[h]);
      if (landed < 0) return landed;
      staged_valid[h] = false;
    }

    if (check_distance > 0) {
      reqs.emplace_back();
      sync.save_current_state(&reqs.back());
    }

    Req advance;
    long rc = sync.synchronized_inputs(dummy_status, &advance);
    if (rc < 0) return rc;
    reqs.push_back(advance);
    sync.current_frame += 1;

    // fake confirmation at current - check_distance so the sync layer never
    // hits the prediction threshold
    int32_t safe_frame = sync.current_frame - check_distance;
    rc = sync.set_last_confirmed_frame(safe_frame, false);
    if (rc < 0) return rc;
    for (int i = 0; i < num_players; ++i)
      dummy_status[i].last_frame = sync.current_frame;

    return static_cast<long>(reqs.size());
  }

  // (sync_test_session.py _adjust_gamestate; reference
  // src/sessions/sync_test_session.rs:178-203)
  long adjust_gamestate_synctest(int32_t frame_to) {
    int32_t start_frame = sync.current_frame;
    int32_t count = start_frame - frame_to;

    reqs.emplace_back();
    long rc = sync.load_frame(frame_to, &reqs.back());
    if (rc < 0) return rc;
    sync.reset_prediction();

    for (int32_t i = 0; i < count; ++i) {
      Req advance;
      rc = sync.synchronized_inputs(dummy_status, &advance);
      if (rc < 0) return rc;
      if (i > 0) {
        reqs.emplace_back();
        sync.save_current_state(&reqs.back());
      }
      sync.current_frame += 1;
      reqs.push_back(advance);
    }
    return sync.current_frame == start_frame ? 0 : SERR_INTERNAL;
  }

  // SyncTest checksum bookkeeping (sync_test_session.py
  // _checksums_consistent / _verify_observation): prune history older than
  // oldest_allowed, then compare-or-record. The wrapper reads the cell
  // checksums (it owns the cells) and calls this per observed frame.
  long st_verify(int32_t frame, const Checksum& csum, int32_t oldest_allowed) {
    for (auto it = st_history.begin(); it != st_history.end();) {
      if (it->first < oldest_allowed)
        it = st_history.erase(it);
      else
        ++it;
    }
    auto it = st_history.find(frame);
    if (it != st_history.end()) {
      if (!(it->second == csum)) {
        last_error_frame = frame;
        return SERR_MISMATCHED_CHECKSUM;
      }
      return 0;
    }
    st_history.emplace(frame, csum);
    return 0;
  }

  // (spectator_session.py advance_frame; reference
  // src/sessions/p2p_spectator_session.rs:109-138)
  long advance_spectator() {
    if (!running) return SERR_NOT_SYNCHRONIZED;
    reqs.clear();

    int32_t behind = spec_last_recv_frame - spec_current_frame;
    int32_t frames_to_advance = behind > max_frames_behind ? catchup_speed : 1;
    for (int32_t i = 0; i < frames_to_advance; ++i) {
      int32_t frame_to_grab = spec_current_frame + 1;
      long rc = inputs_at_frame(frame_to_grab);
      if (rc < 0) return rc;
      // only advance if grabbing the inputs succeeded
      spec_current_frame += 1;
    }
    return static_cast<long>(reqs.size());
  }

  // (spectator_session.py _inputs_at_frame; reference
  // src/sessions/p2p_spectator_session.rs:173-202)
  long inputs_at_frame(int32_t frame_to_grab) {
    SpecSlot* row = &spec_inputs[(frame_to_grab % SPECTATOR_BUFFER) * num_players];
    if (row[0].frame < frame_to_grab)
      return SERR_PREDICTION_THRESHOLD;  // host input not here yet; wait
    if (row[0].frame > frame_to_grab)
      return SERR_SPECTATOR_TOO_FAR_BEHIND;  // ring overwritten; unrecoverable

    reqs.emplace_back();
    Req& r = reqs.back();
    r.type = REQ_ADVANCE;
    r.frame = frame_to_grab;
    r.cell = -1;
    std::memset(r.inputs, 0, sizeof(r.inputs));
    for (int h = 0; h < num_players; ++h) {
      std::memcpy(r.inputs + h * input_size, row[h].buf, input_size);
      bool disconnected = host_connect_status[h].disconnected &&
                          host_connect_status[h].last_frame < frame_to_grab;
      r.statuses[h] = disconnected ? STATUS_DISCONNECTED : STATUS_CONFIRMED;
    }
    return 0;
  }

  ~Session() {
    for (auto& slot : eps)
      if (slot.ep) ggrs_ep_free(slot.ep);
  }
};

}  // namespace

// struct layouts (ggrs_sess_config/_req/_event) live in ggrs_native.h; the
// local sizing constants must stay in lockstep with its fixed array sizes
static_assert(MAX_PLAYERS == 16, "ggrs_native.h pins statuses[16]");
static_assert(MAX_TOTAL_HANDLES == 32, "ggrs_native.h pins player_kinds[32]");
static_assert(MAX_INPUT_SIZE == 64, "ggrs_native.h pins inputs[16*64]");
// ...and the internal tag/error values must equal the public GGRS_* macros
static_assert(SESS_P2P == GGRS_SESS_P2P && SESS_SYNCTEST == GGRS_SESS_SYNCTEST &&
              SESS_SPECTATOR == GGRS_SESS_SPECTATOR, "session type tags drifted");
static_assert(KIND_LOCAL == GGRS_KIND_LOCAL && KIND_REMOTE == GGRS_KIND_REMOTE &&
              KIND_SPECTATOR == GGRS_KIND_SPECTATOR, "player kind tags drifted");
static_assert(
    SERR_NOT_SYNCHRONIZED == GGRS_SERR_NOT_SYNCHRONIZED &&
        SERR_PREDICTION_THRESHOLD == GGRS_SERR_PREDICTION_THRESHOLD &&
        SERR_MISSING_INPUT == GGRS_SERR_MISSING_INPUT &&
        SERR_MISMATCHED_CHECKSUM == GGRS_SERR_MISMATCHED_CHECKSUM &&
        SERR_SPECTATOR_TOO_FAR_BEHIND == GGRS_SERR_SPECTATOR_TOO_FAR_BEHIND &&
        SERR_INVALID_HANDLE == GGRS_SERR_INVALID_HANDLE &&
        SERR_LOCAL_PLAYER == GGRS_SERR_LOCAL_PLAYER &&
        SERR_ALREADY_DISCONNECTED == GGRS_SERR_ALREADY_DISCONNECTED &&
        SERR_INTERNAL == GGRS_SERR_INTERNAL &&
        SERR_CAPACITY == GGRS_SERR_CAPACITY,
    "session error codes drifted from ggrs_native.h");

extern "C" {

void* ggrs_sess_new(const ggrs_sess_config* cfg, uint64_t now_ms) {
  if (cfg->num_players < 1 || cfg->num_players > MAX_PLAYERS) return nullptr;
  if (cfg->input_size < 1 || cfg->input_size > MAX_INPUT_SIZE) return nullptr;
  if (cfg->total_handles < cfg->num_players ||
      cfg->total_handles > MAX_TOTAL_HANDLES)
    return nullptr;
  if (cfg->num_endpoints < 0 || cfg->num_endpoints > MAX_EPS) return nullptr;

  Session* s = new (std::nothrow) Session();
  if (!s) return nullptr;
  s->type = cfg->session_type;
  s->num_players = cfg->num_players;
  s->max_prediction = cfg->max_prediction;
  s->input_size = cfg->input_size;
  s->sparse_saving = cfg->sparse_saving != 0;
  s->desync_interval = cfg->desync_interval;
  s->check_distance = cfg->check_distance;
  s->max_frames_behind = cfg->max_frames_behind;
  s->catchup_speed = cfg->catchup_speed;
  s->total_handles = cfg->total_handles;
  std::copy(cfg->player_kinds, cfg->player_kinds + cfg->total_handles, s->kinds);
  std::copy(cfg->player_endpoints, cfg->player_endpoints + cfg->total_handles,
            s->ep_of_handle);

  if (!s->sync.init(cfg->num_players, cfg->max_prediction, cfg->input_size)) {
    delete s;
    return nullptr;
  }

  Rng rng(cfg->rng_seed);

  if (cfg->session_type == SESS_SPECTATOR) {
    // one endpoint carrying every player handle (builder.py
    // start_spectator_session)
    s->eps.resize(1);
    EndpointSlot& slot = s->eps[0];
    for (int h = 0; h < cfg->num_players; ++h) slot.handles.push_back(h);
    ggrs_ep_config ec{};
    for (size_t i = 0; i < slot.handles.size(); ++i)
      ec.handles[i] = slot.handles[i];
    ec.num_handles = static_cast<long>(slot.handles.size());
    ec.num_players = cfg->num_players;
    ec.local_players = 1;  // irrelevant: spectators never send inputs
    ec.max_prediction = cfg->max_prediction;
    ec.disconnect_timeout_ms = cfg->disconnect_timeout_ms;
    ec.disconnect_notify_start_ms = cfg->disconnect_notify_start_ms;
    ec.fps = cfg->fps;
    ec.input_size = cfg->input_size;
    ec.magic = static_cast<uint16_t>(rng.next() % 0xFFFF) + 1;  // nonzero
    ec.rng_seed = rng.next();
    slot.ep = ggrs_ep_new(&ec, now_ms);
    if (!slot.ep) {
      delete s;
      return nullptr;
    }
    ggrs_ep_synchronize(slot.ep, now_ms);
    s->spec_inputs.resize(SPECTATOR_BUFFER * cfg->num_players);
    s->running = false;
    return s;
  }

  // synctest: every handle local, frame delay applies to all players
  if (cfg->session_type == SESS_SYNCTEST) {
    for (int h = 0; h < cfg->num_players; ++h)
      ggrs_iq_set_frame_delay(s->sync.queues[h], cfg->input_delay);
    s->running = true;
    return s;
  }

  // P2P: one endpoint per unique remote address, grouped by the caller
  // (builder.py start_p2p_session)
  int local_players = 0;
  for (int h = 0; h < cfg->num_players; ++h)
    if (cfg->player_kinds[h] == KIND_LOCAL) {
      ++local_players;
      ggrs_iq_set_frame_delay(s->sync.queues[h], cfg->input_delay);
    }

  s->eps.resize(cfg->num_endpoints);
  for (int h = 0; h < cfg->total_handles; ++h) {
    int e = cfg->player_endpoints[h];
    if (e < 0) continue;
    if (e >= cfg->num_endpoints) {
      delete s;
      return nullptr;
    }
    s->eps[e].handles.push_back(h);
    if (cfg->player_kinds[h] == KIND_SPECTATOR) s->eps[e].is_spectator = true;
  }
  for (auto& slot : s->eps) {
    if (slot.handles.empty() || slot.handles.size() > 16) {
      delete s;
      return nullptr;
    }
    std::sort(slot.handles.begin(), slot.handles.end());
    ggrs_ep_config ec{};
    for (size_t i = 0; i < slot.handles.size(); ++i)
      ec.handles[i] = slot.handles[i];
    ec.num_handles = static_cast<long>(slot.handles.size());
    ec.num_players = cfg->num_players;
    // the host of a spectator sends inputs for all players
    ec.local_players = slot.is_spectator ? cfg->num_players : local_players;
    ec.max_prediction = cfg->max_prediction;
    ec.disconnect_timeout_ms = cfg->disconnect_timeout_ms;
    ec.disconnect_notify_start_ms = cfg->disconnect_notify_start_ms;
    ec.fps = cfg->fps;
    ec.input_size = cfg->input_size;
    ec.magic = static_cast<uint16_t>(rng.next() % 0xFFFF) + 1;
    ec.rng_seed = rng.next();
    slot.ep = ggrs_ep_new(&ec, now_ms);
    if (!slot.ep) {
      delete s;
      return nullptr;
    }
    ggrs_ep_synchronize(slot.ep, now_ms);
  }

  // no remotes -> no synchronization phase needed (p2p_session.py:125-129)
  s->running = s->eps.empty();
  return s;
}

void ggrs_sess_free(void* h) { delete static_cast<Session*>(h); }

long ggrs_sess_state(void* h) {
  return static_cast<Session*>(h)->running ? 1 : 0;
}

int32_t ggrs_sess_current_frame(void* h) {
  Session* s = static_cast<Session*>(h);
  return s->type == SESS_SPECTATOR ? s->spec_current_frame
                                   : s->sync.current_frame;
}

int32_t ggrs_sess_confirmed_frame(void* h) {
  return static_cast<Session*>(h)->confirmed_frame();
}

int32_t ggrs_sess_last_saved_frame(void* h) {
  return static_cast<Session*>(h)->sync.last_saved_frame;
}

long ggrs_sess_frames_ahead(void* h) {
  return static_cast<Session*>(h)->frames_ahead;
}

int32_t ggrs_sess_frames_behind_host(void* h) {
  Session* s = static_cast<Session*>(h);
  return s->spec_last_recv_frame - s->spec_current_frame;
}

int32_t ggrs_sess_last_error_frame(void* h) {
  return static_cast<Session*>(h)->last_error_frame;
}

void ggrs_sess_connect_status(void* h, uint8_t* disc, int32_t* last, long n) {
  Session* s = static_cast<Session*>(h);
  const ConnStatus* src = s->type == SESS_SPECTATOR ? s->host_connect_status
                                                    : s->local_connect_status;
  for (long i = 0; i < n && i < s->num_players; ++i) {
    disc[i] = src[i].disconnected ? 1 : 0;
    last[i] = src[i].last_frame;
  }
}

// Feed one incoming datagram, already routed to the endpoint by the wrapper.
void ggrs_sess_handle_wire(void* h, long ep, const uint8_t* buf, long len,
                           uint64_t now_ms) {
  Session* s = static_cast<Session*>(h);
  if (ep < 0 || ep >= static_cast<long>(s->eps.size())) return;
  ggrs_ep_handle_message(s->eps[ep].ep, buf, len, now_ms);
}

// Drain one outgoing datagram across all endpoints; returns its length and
// endpoint index, or 0 when every queue is empty.
long ggrs_sess_drain_wire(void* h, int32_t* ep_out, uint8_t* buf, long cap) {
  Session* s = static_cast<Session*>(h);
  size_t n = s->eps.size();
  if (n == 0) return 0;
  for (size_t step = 0; step < n; ++step) {
    size_t e = (s->drain_ep + step) % n;
    long len = ggrs_ep_next_send(s->eps[e].ep, buf, cap);
    if (len > 0) {
      *ep_out = static_cast<int32_t>(e);
      s->drain_ep = e;  // keep draining this endpoint before moving on
      return len;
    }
  }
  return 0;
}

void ggrs_sess_poll(void* h, uint64_t now_ms) {
  static_cast<Session*>(h)->poll(now_ms);
}

long ggrs_sess_add_local_input(void* h, long handle, const uint8_t* buf) {
  Session* s = static_cast<Session*>(h);
  if (handle < 0 || handle >= s->num_players) return SERR_INVALID_HANDLE;
  if (s->type == SESS_P2P && s->kinds[handle] != KIND_LOCAL)
    return SERR_INVALID_HANDLE;
  std::memcpy(s->staged_inputs[handle], buf, s->input_size);
  s->staged_valid[handle] = true;
  return 0;
}

long ggrs_sess_advance_frame(void* h, uint64_t now_ms, ggrs_sess_req* out,
                             long cap) {
  Session* s = static_cast<Session*>(h);
  long rc;
  switch (s->type) {
    case SESS_P2P:
      rc = s->advance_p2p(now_ms);
      break;
    case SESS_SYNCTEST:
      rc = s->advance_synctest();
      break;
    case SESS_SPECTATOR:
      rc = s->advance_spectator();
      break;
    default:
      rc = SERR_INTERNAL;
  }
  if (rc < 0) return rc;
  if (rc > cap) return SERR_CAPACITY;  // recoverable: ggrs_sess_copy_requests
  for (long i = 0; i < rc; ++i)
    std::memcpy(&out[i], &s->reqs[i], sizeof(ggrs_sess_req));
  return rc;
}

int32_t ggrs_sess_request_count(void* h) {
  return static_cast<int32_t>(static_cast<Session*>(h)->reqs.size());
}

// Re-copy the last advance's request list (still held by the session) into a
// larger buffer after a SERR_CAPACITY — the advance itself already ran, so
// no state is lost.
long ggrs_sess_copy_requests(void* h, ggrs_sess_req* out, long cap) {
  Session* s = static_cast<Session*>(h);
  long n = static_cast<long>(s->reqs.size());
  if (n > cap) return SERR_CAPACITY;
  for (long i = 0; i < n; ++i)
    std::memcpy(&out[i], &s->reqs[i], sizeof(ggrs_sess_req));
  return n;
}

long ggrs_sess_next_event(void* h, ggrs_sess_event* out) {
  Session* s = static_cast<Session*>(h);
  if (s->events.empty()) return 0;
  const SessEvent& ev = s->events.front();
  out->type = ev.type;
  out->ep = ev.ep;
  out->a = ev.a;
  out->b = ev.b;
  std::memcpy(out->local_checksum, ev.local_checksum, 16);
  std::memcpy(out->remote_checksum, ev.remote_checksum, 16);
  s->events.pop_front();
  return 1;
}

// (p2p_session.py disconnect_player; reference p2p_session.rs:430-456).
// The wrapper validates the handle refers to a non-local player.
long ggrs_sess_disconnect_player(void* h, long handle, uint64_t now_ms) {
  Session* s = static_cast<Session*>(h);
  if (handle < 0 || handle >= s->total_handles) return SERR_INVALID_HANDLE;
  if (s->kinds[handle] == KIND_LOCAL) return SERR_LOCAL_PLAYER;
  if (s->kinds[handle] == KIND_REMOTE) {
    if (s->local_connect_status[handle].disconnected)
      return SERR_ALREADY_DISCONNECTED;
    s->disconnect_player_at_frame(
        static_cast<int>(handle), s->local_connect_status[handle].last_frame,
        now_ms);
  } else {
    s->disconnect_player_at_frame(static_cast<int>(handle), NULL_FRAME, now_ms);
  }
  return 0;
}

long ggrs_sess_network_stats(void* h, long ep, uint64_t now_ms,
                             ggrs_ep_stats* out) {
  Session* s = static_cast<Session*>(h);
  if (ep < 0 || ep >= static_cast<long>(s->eps.size())) return -1;
  return ggrs_ep_network_stats(s->eps[ep].ep, now_ms, out);
}

// Desync detection: which confirmed frame needs its checksum materialized.
// Clears the request; the wrapper answers via ggrs_sess_provide_checksum.
int32_t ggrs_sess_take_checksum_request(void* h) {
  Session* s = static_cast<Session*>(h);
  int32_t f = s->pending_checksum_request;
  s->pending_checksum_request = NULL_FRAME;
  return f;
}

// Record + broadcast a materialized local checksum (p2p_session.py
// _flush_pending_checksum_report, native half).
void ggrs_sess_provide_checksum(void* h, int32_t frame, const uint8_t* csum16,
                                uint64_t now_ms) {
  Session* s = static_cast<Session*>(h);
  Checksum c;
  c.has = true;
  std::memcpy(c.bytes, csum16, 16);
  s->local_checksum_history[frame] = c;
  for (auto& slot : s->eps) {
    if (slot.is_spectator) continue;
    ggrs_ep_send_checksum_report(slot.ep, frame, csum16, now_ms);
  }
}

// SyncTest checksum observation (compare-or-record vs the first-recorded
// history). has == 0 models a save with no checksum (None in Python).
long ggrs_sess_st_verify(void* h, int32_t frame, int has, const uint8_t* csum16,
                         int32_t oldest_allowed) {
  Session* s = static_cast<Session*>(h);
  Checksum c;
  c.has = has != 0;
  if (c.has) std::memcpy(c.bytes, csum16, 16);
  return s->st_verify(frame, c, oldest_allowed);
}

}  // extern "C"
